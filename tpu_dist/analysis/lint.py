"""Layer 1 — AST lint for TPU-hostile idioms (rules TD001-TD005).

Pure ``ast`` walking, no jax import, so it runs anywhere in milliseconds.
The interesting part is *traced-context detection*: TD001/TD005 only apply
inside functions that run under a JAX trace. A function is considered
traced when it is

* decorated with / passed to a trace entry point (``jax.jit``,
  ``shard_map``, ``jax.grad``, ``lax.scan``, ... — ``TRACE_ENTRY_CALLS``),
  including through ``functools.partial``;
* defined lexically inside a traced function (the factory pattern:
  ``make_train_step`` is host code, its nested ``step_local`` is traced); or
* called by name from a traced function in the same module (closure over
  the local call graph, computed to a fixpoint).

This is a heuristic, not a proof — model ``apply`` callbacks crossing
module boundaries are invisible to it — but it covers every idiom the
package actually uses, and misses cost only a lint gap, never a false
build break.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Iterable, Optional

from tpu_dist.analysis.rules import (
    COLLECTIVE_CALL_NONMODULES,
    COLLECTIVE_CALLS,
    COMPAT_MODULE_SUFFIX,
    FRAGILE_IMPORTS,
    HOST_SYNC_BUILTINS,
    HOST_SYNC_CALLS,
    HOST_SYNC_METHODS,
    HOT_FACTORY_REGEX,
    LOG_METHODS,
    LOGGERISH_NAMES,
    NONDETERMINISM_CALLS,
    NONDETERMINISM_PREFIXES,
    RANK_CALL_SUFFIXES,
    RANK_VAR_NAMES,
    TD002_EXEMPT_PARTS,
    TD006_ALLOWED_SILENT,
    TD007_ALLOWED_PARTS,
    TRACE_ENTRY_CALLS,
    Violation,
)

_SUPPRESS_RE = re.compile(r"#\s*tpu-dist:\s*ignore(?:\[([A-Za-z0-9,\s]+)\])?")
_HOT_RE = re.compile(HOT_FACTORY_REGEX)
_PRIMARY_NAMES = {"is_primary", "is_main", "is_main_process", "main_process"}


def lint_paths(paths: Iterable[str], root: Optional[str] = None) -> list[Violation]:
    """Lint every ``.py`` under ``paths``; returns suppression-filtered
    violations with repo-relative file names."""
    root = os.path.abspath(root or os.getcwd())
    out: list[Violation] = []
    for path in paths:
        path = os.path.abspath(path)
        if not os.path.exists(path):
            # a missing path must be loud: os.walk would iterate nothing
            # and the gate would report a false-green "0 violations"
            raise FileNotFoundError(f"lint path does not exist: {path}")
        if os.path.isfile(path):
            out.extend(lint_file(path, root))
            continue
        for dirpath, dirnames, filenames in os.walk(path):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.extend(lint_file(os.path.join(dirpath, fn), root))
    out.sort(key=lambda v: (v.path, v.line, v.rule))
    return out


def lint_file(path: str, root: Optional[str] = None) -> list[Violation]:
    root = os.path.abspath(root or os.getcwd())
    rel = os.path.relpath(os.path.abspath(path), root).replace(os.sep, "/")
    with open(path, encoding="utf-8") as f:
        source = f.read()
    return lint_source(source, rel)


def lint_source(source: str, rel_path: str) -> list[Violation]:
    """Lint one file's source. ``rel_path`` is used for reporting AND for
    path-scoped rules (TD004's compat-module exemption)."""
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [Violation("TD000", rel_path, e.lineno or 0, f"syntax error: {e.msg}")]
    lines = source.splitlines()
    lint = _FileLint(tree, lines, rel_path)
    out = [v for v in lint.run() if not lint.suppressed(v)]
    out.sort(key=lambda v: (v.line, v.col, v.rule))
    return out


class _FileLint:
    def __init__(self, tree: ast.Module, lines: list[str], rel_path: str):
        self.tree = tree
        self.lines = lines
        self.rel_path = rel_path
        self.parent: dict[ast.AST, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                self.parent[child] = node
        self.aliases = self._collect_aliases()
        self.funcs_by_name: dict[str, list[ast.AST]] = {}
        self.all_funcs: list[ast.AST] = []
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.all_funcs.append(node)
                self.funcs_by_name.setdefault(node.name, []).append(node)
        self.traced = self._find_traced()
        self.suppressions = self._collect_suppressions()

    # -- plumbing ----------------------------------------------------------

    def _collect_aliases(self) -> dict[str, str]:
        aliases: dict[str, str] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0]
                    )
            elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
                for a in node.names:
                    if a.name != "*":
                        aliases[a.asname or a.name] = f"{node.module}.{a.name}"
        return aliases

    def resolve(self, node: ast.AST) -> Optional[str]:
        """Dotted path of a Name/Attribute chain with import aliases
        substituted: ``np.random.default_rng`` → ``numpy.random.default_rng``."""
        parts: list[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        parts.append(node.id)
        parts.reverse()
        head = self.aliases.get(parts[0], parts[0])
        return ".".join([head] + parts[1:])

    def _collect_suppressions(self) -> dict[int, Optional[set]]:
        """Map line → suppressed rule ids (None = all). A marker on a code
        line covers that line; a marker inside a comment block covers the
        next statement line (so multi-line explanations work)."""
        sup: dict[int, Optional[set]] = {}
        for i, line in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(line)
            if not m:
                continue
            ids_str = m.group(1)
            ids = {s.strip() for s in ids_str.split(",")} if ids_str else None
            targets = [i]
            if line.strip().startswith("#"):
                j = i + 1
                while j <= len(self.lines) and (
                    not self.lines[j - 1].strip()
                    or self.lines[j - 1].strip().startswith("#")
                ):
                    j += 1
                if j <= len(self.lines):
                    targets.append(j)
            for t in targets:
                if ids is None or sup.get(t, set()) is None:
                    sup[t] = None
                else:
                    sup[t] = set(sup.get(t) or set()) | ids
        return sup

    def suppressed(self, v: Violation) -> bool:
        ids = self.suppressions.get(v.line, False)
        if ids is False:
            return False
        return ids is None or v.rule in ids

    def _snippet(self, node: ast.AST) -> str:
        line = getattr(node, "lineno", 0)
        return self.lines[line - 1].strip() if 0 < line <= len(self.lines) else ""

    def _violation(self, rule: str, node: ast.AST, message: str) -> Violation:
        return Violation(
            rule,
            self.rel_path,
            getattr(node, "lineno", 0),
            message,
            col=getattr(node, "col_offset", 0),
            snippet=self._snippet(node),
        )

    # -- traced-context detection -----------------------------------------

    def _is_trace_entry(self, func_expr: ast.AST) -> bool:
        resolved = self.resolve(func_expr)
        if resolved is None:
            return False
        if resolved in TRACE_ENTRY_CALLS:
            return True
        # bare names that came from `from jax import jit` etc. resolve above;
        # accept any compat-module shard_map re-export
        return resolved.endswith(".shard_map")

    def _find_traced(self) -> set:
        traced: set = set()
        # roots: decorators and direct references in trace-entry calls
        for fn in self.all_funcs:
            for dec in fn.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                if self._is_trace_entry(target):
                    traced.add(fn)
                elif (
                    isinstance(dec, ast.Call)
                    and self.resolve(dec.func) == "functools.partial"
                    and dec.args
                    and self._is_trace_entry(dec.args[0])
                ):
                    traced.add(fn)
        for node in ast.walk(self.tree):
            if not (isinstance(node, ast.Call) and self._is_trace_entry(node.func)):
                continue
            for arg in list(node.args) + [k.value for k in node.keywords]:
                if isinstance(arg, ast.Name):
                    for fn in self.funcs_by_name.get(arg.id, []):
                        traced.add(fn)
                elif isinstance(arg, ast.Lambda):
                    traced.add(arg)
        # closure: lexically-nested defs + module-local call graph
        changed = True
        while changed:
            changed = False
            for fn in list(traced):
                for sub in ast.walk(fn):
                    if sub is fn:
                        continue
                    if isinstance(
                        sub, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
                    ) and sub not in traced:
                        traced.add(sub)
                        changed = True
                    if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name):
                        for callee in self.funcs_by_name.get(sub.func.id, []):
                            if callee not in traced:
                                traced.add(callee)
                                changed = True
        return traced

    # -- rank-0 guard recognition (TD002) ---------------------------------

    def _is_rank_expr(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Call):
            resolved = self.resolve(node.func) or ""
            return resolved.split(".")[-1] in RANK_CALL_SUFFIXES
        if isinstance(node, ast.Name):
            return node.id in RANK_VAR_NAMES
        if isinstance(node, ast.Attribute):
            return node.attr in RANK_VAR_NAMES
        return False

    def _is_primary_expr(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Call):
            resolved = self.resolve(node.func) or ""
            return resolved.split(".")[-1] in _PRIMARY_NAMES
        if isinstance(node, (ast.Name, ast.Attribute)):
            name = node.id if isinstance(node, ast.Name) else node.attr
            return name in _PRIMARY_NAMES
        return False

    def _test_polarity(self, test: ast.AST) -> Optional[bool]:
        """True = test passes only on rank 0; False = only on rank != 0;
        None = not a rank test. Handles ``== 0``/``!= 0``/``> 0``, bare
        truthiness, ``not`` inversion, ``is_primary()`` spellings, and
        ``and``-conjunctions containing a rank test."""
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            inner = self._test_polarity(test.operand)
            return None if inner is None else not inner
        if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.And):
            for sub in test.values:
                pol = self._test_polarity(sub)
                if pol is not None:
                    return pol  # rank0 AND x still implies rank0 when true
            return None
        if self._is_primary_expr(test):
            return True
        if self._is_rank_expr(test):
            return False  # `if rank:` is true only off rank 0
        if isinstance(test, ast.Compare) and len(test.ops) == 1:
            left, op, right = test.left, test.ops[0], test.comparators[0]
            if isinstance(right, ast.Constant) and right.value == 0:
                rank_side = left
            elif isinstance(left, ast.Constant) and left.value == 0:
                rank_side, op = right, _flip(op)
            else:
                return None
            if not self._is_rank_expr(rank_side):
                return None
            if isinstance(op, ast.Eq):
                return True
            if isinstance(op, (ast.NotEq, ast.Gt)):
                return False
        return None

    def _is_rank0_guarded(self, node: ast.AST) -> bool:
        # (a) ancestor `if` taking the rank-0 branch
        child = node
        anc = self.parent.get(node)
        while anc is not None:
            if isinstance(anc, ast.If):
                pol = self._test_polarity(anc.test)
                if pol is not None:
                    in_body = any(child is s for s in anc.body)
                    in_orelse = any(child is s for s in anc.orelse)
                    if (pol and in_body) or (not pol and in_orelse):
                        return True
            child, anc = anc, self.parent.get(anc)
        # (b) early-return guard earlier in the enclosing function:
        #     `if rank != 0: return` before this statement
        fn = self._enclosing_function(node)
        if fn is not None:
            for stmt in fn.body:
                if getattr(stmt, "lineno", 10**9) >= getattr(node, "lineno", 0):
                    break
                if (
                    isinstance(stmt, ast.If)
                    and self._test_polarity(stmt.test) is False
                    and any(isinstance(s, (ast.Return, ast.Raise)) for s in stmt.body)
                    and not stmt.orelse
                ):
                    return True
        return False

    def _rank_guard(self, node: ast.AST):
        """The rank-dependent control flow that gates ``node`` (TD008):
        an ancestor ``if`` whose test is a rank test of EITHER polarity —
        unlike :meth:`_is_rank0_guarded`, which only certifies the rank-0
        branch — or an earlier rank-early-return in the enclosing
        function, after which the remaining body runs on a rank subset.
        Returns the guarding statement, or None."""
        child = node
        anc = self.parent.get(node)
        while anc is not None:
            if (
                isinstance(anc, ast.If)
                and self._test_polarity(anc.test) is not None
                and (
                    any(child is s for s in anc.body)
                    or any(child is s for s in anc.orelse)
                )
            ):
                return anc
            child, anc = anc, self.parent.get(anc)
        fn = self._enclosing_function(node)
        if fn is not None:
            for stmt in fn.body:
                if getattr(stmt, "lineno", 10**9) >= getattr(node, "lineno", 0):
                    break
                if (
                    isinstance(stmt, ast.If)
                    and self._test_polarity(stmt.test) is not None
                    and any(
                        isinstance(s, (ast.Return, ast.Raise)) for s in stmt.body
                    )
                    and not stmt.orelse
                ):
                    return stmt
        return None

    def _enclosing_function(self, node: ast.AST):
        anc = self.parent.get(node)
        while anc is not None:
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return anc
            anc = self.parent.get(anc)
        return None

    # -- the rules ---------------------------------------------------------

    def run(self) -> list[Violation]:
        out: list[Violation] = []
        seen: set = set()

        def emit(rule: str, node: ast.AST, msg: str) -> None:
            key = (rule, getattr(node, "lineno", 0), getattr(node, "col_offset", 0))
            if key not in seen:
                seen.add(key)
                out.append(self._violation(rule, node, msg))

        self._check_imports(emit)
        for fn in self.traced:
            self._check_traced_body(fn, emit)
        self._check_io(emit)
        self._check_bare_print(emit)
        self._check_jit_donate(emit)
        self._check_silent_except(emit)
        self._check_rank_guarded_collective(emit)
        return out

    def _check_imports(self, emit) -> None:  # TD004
        if self.rel_path.endswith(COMPAT_MODULE_SUFFIX):
            return
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ImportFrom) and node.module:
                banned = FRAGILE_IMPORTS.get(node.module)
                for a in node.names:
                    if (banned and (a.name in banned or "*" in banned)) or (
                        FRAGILE_IMPORTS.get(f"{node.module}.{a.name}")
                    ):
                        emit(
                            "TD004",
                            node,
                            f"`from {node.module} import {a.name}` moved between "
                            "JAX releases; import it from tpu_dist.comm.compat",
                        )
            elif isinstance(node, ast.Import):
                for a in node.names:
                    if a.name in FRAGILE_IMPORTS and "*" in FRAGILE_IMPORTS[a.name]:
                        emit(
                            "TD004",
                            node,
                            f"`import {a.name}` moved between JAX releases; "
                            "use tpu_dist.comm.compat",
                        )

    def _check_traced_body(self, fn: ast.AST, emit) -> None:  # TD001 / TD005
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            resolved = self.resolve(node.func)
            if resolved in HOST_SYNC_CALLS:
                emit("TD001", node, f"`{resolved}` forces a host sync under trace")
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in HOST_SYNC_METHODS
                and not node.args
            ):
                emit(
                    "TD001",
                    node,
                    f"`.{node.func.attr}()` forces a host sync under trace",
                )
            elif (
                isinstance(node.func, ast.Name)
                and node.func.id in HOST_SYNC_BUILTINS
                and node.args
                and not isinstance(node.args[0], ast.Constant)
            ):
                emit(
                    "TD001",
                    node,
                    f"`{node.func.id}()` on a traced value blocks on device "
                    "readback (host sync)",
                )
            if resolved is not None and (
                resolved in NONDETERMINISM_CALLS
                or resolved.startswith(NONDETERMINISM_PREFIXES)
            ):
                emit(
                    "TD005",
                    node,
                    f"`{resolved}` is evaluated ONCE at trace time and baked "
                    "into the program; use jax.random / pass values in",
                )

    def _check_io(self, emit) -> None:  # TD002
        if any(part in self.rel_path for part in TD002_EXEMPT_PARTS):
            return
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            kind = self._io_kind(node)
            if kind is None or self._is_rank0_guarded(node):
                continue
            emit(
                "TD002",
                node,
                f"unguarded {kind} runs on EVERY process; wrap in "
                "`if process_index() == 0` (or rank0_print/get_logger)",
            )

    def _io_kind(self, node: ast.Call) -> Optional[str]:
        func = node.func
        if isinstance(func, ast.Name):
            if func.id == "print":
                return "print()"
            if func.id == "open":
                mode = None
                if len(node.args) >= 2:
                    mode = node.args[1]
                for k in node.keywords:
                    if k.arg == "mode":
                        mode = k.value
                if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
                    if any(c in mode.value for c in "wax+"):
                        return f"open(mode={mode.value!r}) file write"
            return None
        if isinstance(func, ast.Attribute):
            if func.attr in ("write_text", "write_bytes"):
                return f".{func.attr}() file write"
            if func.attr in LOG_METHODS:
                resolved = self.resolve(func.value) or ""
                base = func.value
                basename = (
                    base.id
                    if isinstance(base, ast.Name)
                    else base.attr if isinstance(base, ast.Attribute) else ""
                )
                if resolved == "logging" or resolved.startswith("logging."):
                    return f"logging.{func.attr}()"
                if any(t in basename.lower() for t in LOGGERISH_NAMES):
                    return f"{basename}.{func.attr}()"
        return None

    def _check_bare_print(self, emit) -> None:  # TD007
        """Stricter sibling of TD002: ANY bare ``print(`` outside the
        designated logging layer — a rank-0 guard makes it correct but
        still un-grep-able and un-silenceable; the discipline is one
        output layer (rank0_print/get_logger)."""
        if any(part in self.rel_path for part in TD007_ALLOWED_PARTS):
            return
        for node in ast.walk(self.tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "print"
            ):
                emit(
                    "TD007",
                    node,
                    "bare print() bypasses the logging layer; use "
                    "rank0_print/get_logger (tpu_dist.metrics.logging) — or "
                    "inline-ignore with the reason this sink is deliberate",
                )

    def _exc_type_names(self, t: ast.AST) -> list[str]:
        """Dotted names of the handled exception type(s); '<dynamic>' for
        anything unresolvable (a computed type never passes the allowlist)."""
        if isinstance(t, ast.Tuple):
            out: list[str] = []
            for e in t.elts:
                out.extend(self._exc_type_names(e))
            return out
        resolved = self.resolve(t)
        return [resolved] if resolved else ["<dynamic>"]

    def _check_silent_except(self, emit) -> None:  # TD006
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None:
                emit(
                    "TD006",
                    node,
                    "bare `except:` also catches SystemExit/"
                    "KeyboardInterrupt and hides the real failure; catch a "
                    "concrete exception type",
                )
                continue
            # "silent" = the body does literally nothing: pass / `...`
            silent = all(
                isinstance(s, ast.Pass)
                or (
                    isinstance(s, ast.Expr)
                    and isinstance(s.value, ast.Constant)
                )
                for s in node.body
            )
            if not silent:
                continue
            names = self._exc_type_names(node.type)
            if all(n.split(".")[-1] in TD006_ALLOWED_SILENT for n in names):
                continue
            emit(
                "TD006",
                node,
                f"`except {', '.join(names)}: pass` silently swallows the "
                "failure — on a multi-process job the first fault then "
                "surfaces as a collective deadlock; log it, re-raise, or "
                "narrow to an allowlisted benign type "
                f"({', '.join(sorted(TD006_ALLOWED_SILENT))})",
            )

    def _check_rank_guarded_collective(self, emit) -> None:  # TD008
        """A collective call site gated by rank-dependent control flow —
        the cross-host deadlock shape: only the guarded ranks reach the
        collective, the rest block in whatever collective comes NEXT and
        the job dies minutes later with an opaque timeout. Compute the
        collective on every rank and guard the rank-local *action*
        (print/write) instead. ``jnp.where``-style masking keeps the op
        collective-uniform; rank-guarded call sites never are."""
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            resolved = self.resolve(node.func)
            if resolved is None and isinstance(node.func, ast.Attribute):
                last = node.func.attr
            elif resolved is not None:
                if resolved.startswith(COLLECTIVE_CALL_NONMODULES):
                    continue
                last = resolved.split(".")[-1]
            else:
                continue
            if last not in COLLECTIVE_CALLS:
                continue
            guard = self._rank_guard(node)
            if guard is None:
                continue
            emit(
                "TD008",
                node,
                f"collective `{last}` is reachable only under the rank-"
                f"dependent guard at line {guard.lineno} — ranks that "
                "skip it block in the next matching collective "
                "(cross-host deadlock); hoist the collective out of the "
                "guard (compute everywhere, act on one rank)",
            )

    def _check_jit_donate(self, emit) -> None:  # TD003
        for node in ast.walk(self.tree):
            if not (
                isinstance(node, ast.Call) and self.resolve(node.func) == "jax.jit"
            ):
                continue
            kwargs = {k.arg for k in node.keywords}
            if kwargs & {"donate_argnums", "donate_argnames"}:
                continue
            fn = self._enclosing_function(node)
            if fn is None or not _HOT_RE.match(fn.name):
                continue
            emit(
                "TD003",
                node,
                f"jax.jit inside hot-path factory `{fn.name}` without "
                "donate_argnums: the old TrainState stays live across the "
                "update (2x peak HBM)",
            )


def _flip(op: ast.cmpop) -> ast.cmpop:
    if isinstance(op, ast.Gt):
        return ast.Lt()
    if isinstance(op, ast.Lt):
        return ast.Gt()
    return op
