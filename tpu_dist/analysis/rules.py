"""Rule registry for the distributed-training lint (Layer 1) and jaxpr
audit (Layer 2).

Each rule ports one correctness/perf discipline that the reference repo
states only as prose (rank-0 logging, ``no_sync`` accumulation, SyncBN
placement — SURVEY §2-3) or that the TPU literature identifies as a silent
killer (sharding-annotation and host-sync mistakes: Xu et al.
arXiv:2004.13336, Kumar et al. arXiv:2011.03641). The linter walks the
package with ``ast``; the audit traces registered step builders and
inspects the closed jaxpr. Both report :class:`Violation` records keyed by
these IDs.

Suppression: append ``# tpu-dist: ignore[TDxxx]`` (with a reason) to the
flagged line — or the line directly above — or record the finding in the
checked-in baseline (see ``tpu_dist/analysis/baseline.py``). Every rule is
documented in ``docs/analysis.md``.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

# --------------------------------------------------------------------------
# Rule table. TD0xx = AST lint (Layer 1); TD1xx = jaxpr audit (Layer 2).
# --------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Rule:
    id: str
    name: str
    summary: str


RULES: dict[str, Rule] = {
    r.id: r
    for r in [
        Rule(
            "TD001",
            "host-sync-in-traced-fn",
            "host-synchronizing call (.item()/float()/np.asarray/"
            "jax.device_get/.block_until_ready()) inside a traced/jitted "
            "function — forces a device round-trip every step",
        ),
        Rule(
            "TD002",
            "unguarded-nonrank0-io",
            "print/log/file-write not guarded by process_index() == 0 — "
            "every host duplicates the I/O (reference rank-0 discipline, "
            "tutorials/2 §3)",
        ),
        Rule(
            "TD003",
            "jit-missing-donate",
            "jax.jit on a hot-path step/epoch builder without "
            "donate_argnums — doubles peak HBM by keeping the old "
            "TrainState alive across the update",
        ),
        Rule(
            "TD004",
            "version-fragile-jax-import",
            "direct import of a JAX API that moved between releases "
            "(shard_map/pjit) — must route through tpu_dist.comm.compat",
        ),
        Rule(
            "TD005",
            "nondeterminism-in-traced-fn",
            "np.random/time.time()/stdlib random inside a traced function "
            "— baked in as a trace-time constant, NOT fresh per step",
        ),
        Rule(
            "TD006",
            "silent-exception-swallow",
            "`except ...: pass` (outside the benign allowlist) or bare "
            "`except:` silently swallows failures — in a multi-process job "
            "this hides the first fault until a collective deadlocks; "
            "re-raise, log, or narrow the type",
        ),
        Rule(
            "TD007",
            "bare-print-outside-logging-layer",
            "bare `print(` outside the metrics/logging allowlist — even "
            "rank-0-guarded prints bypass the one grep-able output layer "
            "(rank0_print / get_logger / ProgressMeter); route through it "
            "or inline-ignore with the audit reason",
        ),
        Rule(
            "TD008",
            "rank-guarded-collective",
            "a collective call site reachable only under rank-/process-"
            "dependent control flow — the guarded ranks enter the "
            "collective, the rest never do, and the job dies as a "
            "cross-host deadlock minutes later; hoist the collective out "
            "of the guard (compute on every rank, act on one)",
        ),
        Rule(
            "TD101",
            "collective-budget-mismatch",
            "jaxpr collective count differs from the parallelism config's "
            "budget — an accidental extra (or missing) cross-replica "
            "reduce in the compiled step",
        ),
        Rule(
            "TD102",
            "unexpected-transfer-op",
            "device_put / host transfer op inside the compiled step jaxpr "
            "— host↔device traffic on the hot path",
        ),
        Rule(
            "TD103",
            "bf16-promotion-over-budget",
            "more bf16→f32 convert_element_type ops than the mixed-"
            "precision path declares — an implicit promotion is silently "
            "doing f32 math",
        ),
        Rule(
            "TD105",
            "fault-injection-not-noop",
            "the traced train step differs between fault injection OFF and "
            "an armed --fault_plan — injection points must be host-side "
            "no-ops that never enter the compiled program "
            "(resilience/faults.py contract)",
        ),
        Rule(
            "TD106",
            "telemetry-not-noop",
            "the traced train step differs between telemetry OFF and "
            "armed spans/counters/heartbeat — run telemetry must be "
            "host-side only and add no per-step device work "
            "(tpu_dist.obs contract, docs/observability.md)",
        ),
        Rule(
            "TD107",
            "device-metrics-cost-leak",
            "the --device_metrics contract broke: flag OFF must leave the "
            "traced train step byte-identical, flag ON must add zero "
            "collectives and zero transfer ops on the pure-DP path (the "
            "health scalars ride the post-pmean gradients and the "
            "existing single per-step fetch — obs/device_stats.py)",
        ),
        Rule(
            "TD108",
            "profile-trigger-not-noop",
            "the traced train step differs between no profiler and an "
            "armed/capturing triggered profiler — capture control must "
            "stay host-side (arm flags, jax.profiler start/stop around "
            "the unmodified step; obs/profile.py contract)",
        ),
        Rule(
            "TD109",
            "live-export-not-noop",
            "the traced train step differs between live telemetry OFF and "
            "an armed OpenMetrics exporter + alert engine (exposition "
            "published, /metrics scraped, threshold rules fired) — live "
            "export and alerting must stay host-side (obs/export.py + "
            "obs/alerts.py contract)",
        ),
        Rule(
            "TD110",
            "xprof-hook-not-noop",
            "the traced train step differs between no profiler and a "
            "triggered profiler whose AUTO-ANALYZE hook is armed — across "
            "arm, capture-open, and capture-closed-and-analyzed states "
            "(obs/xprof.py read-back + cost-model calibration must stay "
            "host-side file crunching; obs/profile.py contract)",
        ),
        Rule(
            "TD111",
            "elastic-resume-not-noop",
            "the traced train step of an elastic-resumed trainer (state "
            "restored from a checkpoint written at a DIFFERENT dp extent "
            "and remapped) differs from a fresh-start trainer at the same "
            "new world size — the remap must be restore-time host work "
            "that reproduces exactly the shapes/dtypes a fresh "
            "construction gets (tpu_dist/elastic/remap.py contract)",
        ),
        Rule(
            "TD112",
            "elastic-grow-not-noop",
            "the traced train step of a GROW-resumed trainer (state "
            "restored from a checkpoint written at a SMALLER dp extent "
            "and remapped up onto more devices) differs from a "
            "fresh-start trainer at the same larger world size — the "
            "scale-up remap must be restore-time host work that "
            "reproduces exactly the shapes/dtypes a fresh construction "
            "gets (the grow mirror of TD111; tpu_dist/elastic/remap.py "
            "contract)",
        ),
        Rule(
            "TD113",
            "flight-recorder-not-noop",
            "the traced train step differs between crash forensics OFF "
            "and an armed flight recorder + faulthandler (ring slots "
            "written, excepthooks wrapped, span-open listener tapped, "
            "SIGUSR1 all-threads dump registered and fired) — crash "
            "forensics must stay host-side file I/O on the step "
            "boundary (obs/flight.py contract, docs/observability.md "
            "'Crash forensics')",
        ),
        Rule(
            "TD114",
            "serving-slo-not-noop",
            "the traced serving forward step differs between bare "
            "inference and the full serve telemetry/SLO kit armed "
            "(streaming latency histograms observing, queue/occupancy "
            "gauges published, SLO alert engine fired, histogram "
            "exposition rendered and parsed back, span recorder "
            "tapped) — serving observability must stay host-side "
            "arithmetic around the unmodified compiled step "
            "(tpu_dist/serve contract, docs/serving.md)",
        ),
        Rule(
            "TD115",
            "memory-ledger-not-noop",
            "the traced train step differs between the HBM ledger OFF "
            "and the full memory kit armed (static per-leaf ledger over "
            "a real sharded state, live-buffer census, allocator stats "
            "read, census/allocator reconciliation, mem.* gauges "
            "published, pre-flight feasibility check, memory_analysis "
            "waterfall of an AOT probe, RESOURCE_EXHAUSTED parser "
            "exercised) — memory observability must stay host-side "
            "metadata arithmetic (obs/memory.py contract, "
            "docs/observability.md 'HBM ledger & OOM forensics')",
        ),
        Rule(
            "TD116",
            "compiled-collectives-match-predicted",
            "the optimized HLO's collective wire accounting disagrees "
            "with the jaxpr-level TD104 ring model (elements exact; "
            "integer/quantized legs byte-exact; float legs exact modulo "
            "the backend's declared bf16->f32 normalization) — one of the "
            "two accountings is lying about what the step moves "
            "(tpu_dist/analysis/shardlint.py, docs/shard_report.md)",
        ),
        Rule(
            "TD117",
            "unintended-reshard-in-compiled-step",
            "the optimized HLO contains a collective the jaxpr-level "
            "inventory did not predict (an unpredicted op kind, or "
            "per-kind wire bytes beyond the prediction) — GSPMD inserted "
            "an implicit reshard, usually a bad in_shardings/out_shardings "
            "gathering state the step expected resident "
            "(tpu_dist/analysis/shardlint.py)",
        ),
        Rule(
            "TD118",
            "plan-must-verify",
            "the --auto_shard planner's chosen plan was priced on a "
            "collective inventory that does not match what the fresh "
            "shardlint compile of the same family emits (per-kind "
            "op/element/byte counts, total wire bytes) — the ranking "
            "rests on a stale or perturbed cost basis; the "
            "--inject-miscost probe must be caught or the detector is "
            "dead (tpu_dist/analysis/planner.py, docs/planner.md)",
        ),
        Rule(
            "TD119",
            "planner-error-tracked",
            "after a profiled run, the predicted-vs-achieved step time "
            "drift (|predicted - achieved| / achieved) must land in "
            "history as planner_error_frac ('plan' records, schema v12) "
            "and gate through `obs compare` METRIC_DIRECTIONS (lower is "
            "better) — planner drift is a regression like any other "
            "(tpu_dist/analysis/planner.py, obs/compare.py, "
            "docs/planner.md)",
        ),
        Rule(
            "TD120",
            "async-ckpt-semantics-preserved",
            "the async sharded checkpoint path (--sharded_ckpt + "
            "--async_ckpt) must leave the traced train step byte-identical "
            "to synchronous saves AND restore bit-exact to the synchronous "
            "sharded format; the injected EIO and SIGTERM fault probes "
            "must surface through the drain path — an uncaught probe "
            "means the detector is dead (CLI exit 2) "
            "(tpu_dist/ckpt/checkpoint.py, docs/checkpointing.md)",
        ),
        Rule(
            "TD121",
            "tuner-knob-schedule-only",
            "an overlap-autotuner knob (pmean_fusion, rs_ag_chunks, "
            "quant_chunk) changed the HLO payload-byte inventory shardlint "
            "pins, or failed to move the collective schedule at all — "
            "knobs must be semantics-preserving schedule transforms by "
            "construction, and a payload drift or a vacuous knob is a "
            "lying search space; the --inject-payload probe must be "
            "caught or the detector is dead (CLI exit 2) "
            "(tpu_dist/analysis/overlap.py, docs/analysis.md)",
        ),
        Rule(
            "TD122",
            "tenancy-arbitration-control-plane-only",
            "the traced train step or the jitted serving forward CHANGED "
            "when the multi-tenant arbitration kit was armed (serve-gauge "
            "scrape through read_signals, kind-aware fleet policy driven "
            "to a genuinely fired SLO preemption, the cooperative SIGTERM "
            "flag raised, load-shedding admission refusing work) — "
            "train/serve co-scheduling must stay host-side control-plane "
            "arithmetic around the unmodified compiled programs, and a "
            "probe where the preemption never fires is vacuous "
            "(tpu_dist/fleet/scheduler.py, tpu_dist/serve/engine.py, "
            "docs/resilience.md 'Multi-tenant pod')",
        ),
        Rule(
            "TD123",
            "pod-telemetry-control-plane-only",
            "the traced train step or the jitted serving forward CHANGED "
            "when the pod telemetry plane was armed (two-run federated "
            "hub scrape mid-audit, the arbiter fed from the hub snapshot, "
            "a donate→grant pair chained under ONE decision_id propagated "
            "through allocation file → relaunch env → resume record, the "
            "serve-preempt gap charged to preempt_for_serve_s with the "
            "bucket partition exact) — federation and causal tracing must "
            "stay host-side file arithmetic, and a probe that aggregates "
            "zero runs or loses the id mid-chain is vacuous "
            "(tpu_dist/obs/hub.py, tpu_dist/fleet/scheduler.py, "
            "docs/observability.md 'Pod telemetry hub')",
        ),
        Rule(
            "TD124",
            "archive-gate-not-vacuous",
            "the longitudinal archive's regression machinery went dead or "
            "device-side: an injected past-band candidate must come back "
            "REGRESSED through the MAD-band gate, an injected improvement "
            "must come back clean, an injected changepoint must be "
            "localized by --blame to the exact archived record, ingest "
            "must be idempotent by fingerprint with stale re-emissions "
            "flagged and excluded from the band — and arming the full "
            "ingest+gate+trend kit must leave the traced train step "
            "byte-identical (tpu_dist/obs/archive.py, "
            "docs/observability.md 'Longitudinal archive & trend gating')",
        ),
        Rule(
            "TD104",
            "quantized-wire-bytes-over-budget",
            "gradient-collective payload bytes of a quantized wire format "
            "exceed the declared ratio of its reference mode (int8 must "
            "stay ≤0.5× bf16 / ≤0.25× f32) — a wire leg silently "
            "decompressed",
        ),
    ]
}


@dataclasses.dataclass
class Violation:
    rule: str
    path: str  # repo-relative file, or "<jaxpr:case>" for Layer 2
    line: int
    message: str
    col: int = 0
    snippet: str = ""

    def format_text(self) -> str:
        loc = f"{self.path}:{self.line}:{self.col}"
        return f"{loc}: {self.rule} {self.message}"

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    def baseline_key(self) -> tuple:
        """Line numbers drift; baseline entries match on the line's text."""
        return (self.rule, self.path, self.snippet.strip())


# --------------------------------------------------------------------------
# Lint configuration (Layer 1 knobs, one place).
# --------------------------------------------------------------------------

# Entry points whose function arguments run under trace (TD001/TD005 scope).
TRACE_ENTRY_CALLS = {
    "jax.jit",
    "jax.pmap",
    "jax.vmap",
    "jax.grad",
    "jax.value_and_grad",
    "jax.checkpoint",
    "jax.remat",
    "jax.lax.scan",
    "jax.lax.map",
    "jax.lax.cond",
    "jax.lax.while_loop",
    "jax.lax.fori_loop",
    "jax.lax.associative_scan",
    "jax.experimental.shard_map.shard_map",
    "jax.shard_map",
    "tpu_dist.comm.compat.shard_map",
}

# Fully-resolved call targets that force a host sync (TD001).
HOST_SYNC_CALLS = {
    "jax.device_get",
    "jax.block_until_ready",
    "numpy.asarray",
    "numpy.array",
    "numpy.asanyarray",
    "numpy.ascontiguousarray",
}
# Method names that force a host sync on any receiver (TD001).
HOST_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
# Builtins that force a sync when applied to a traced value (TD001).
HOST_SYNC_BUILTINS = {"float", "int", "bool"}

# Nondeterministic-at-trace-time call prefixes (TD005).
NONDETERMINISM_PREFIXES = ("numpy.random.", "random.")
NONDETERMINISM_CALLS = {
    "time.time",
    "time.perf_counter",
    "time.monotonic",
    "time.time_ns",
}

# Logger-ish method names for TD002 (receiver name must look like a logger).
LOG_METHODS = {"debug", "info", "warning", "error", "critical", "exception", "log"}
LOGGERISH_NAMES = ("log", "logger")

# Rank-0 guard spellings TD002 recognizes in `if` tests.
RANK_CALL_SUFFIXES = ("process_index", "is_primary", "get_rank")
RANK_VAR_NAMES = {"rank", "local_rank", "process_id", "proc_id", "process_index", "pid"}

# Modules exempt from TD002: host-side tooling that never runs inside a
# multi-process training job (the analysis and obs CLIs' report output —
# `obs memory`'s ledger/OOM reports included, the fleet controller — the
# scheduler/drill/capacity census run in the single arbiter/launcher
# process, whose FILES are the control channel the runs' probes read —
# and the serve CLI/drill, which run in the single serving/operator
# process). obs/memory.py itself is NOT exempt: its in-job artifact
# writes (oom.json) carry inline ignores with the per-rank-path
# justification instead.
TD002_EXEMPT_PARTS = (
    "tpu_dist/analysis/", "tpu_dist/obs/__main__.py", "tpu_dist/fleet/",
    "tpu_dist/serve/__main__.py", "tpu_dist/serve/drill.py",
)

# TD007 allowlist: the designated output layer (rank0_print/get_logger and
# the ProgressMeter display sink, which carries the rank-0 guard itself)
# plus pure-CLI report modules whose stdout IS the product — the `obs`
# subcommands (summarize/compare/pod/xprof/postmortem/memory) all print
# through obs/__main__.py. Everything else must route prints through the
# logging layer — the statically-enforced version of the rank-0
# discipline the reference only documents.
TD007_ALLOWED_PARTS = (
    "tpu_dist/metrics/logging.py",
    "tpu_dist/metrics/meters.py",
    "tpu_dist/analysis/",
    "tpu_dist/obs/__main__.py",
    "tpu_dist/serve/__main__.py",
    "tpu_dist/serve/drill.py",
)

# TD003 scope: jit calls inside these factory-name patterns are "hot path".
HOT_FACTORY_REGEX = r"^(make|build)_.*(step|epoch|train|update)"

# TD008: call targets that are (or transitively drive) a cross-process
# collective, matched on the LAST dotted segment — the jax.lax primitives,
# the tpu_dist.comm.collectives wrappers (reduce_mean/barrier/...), the
# quantized two-stage reduce, and the multihost_utils host-level syncs.
# Any of these reachable only under a rank-dependent `if` is the classic
# deadlock shape: the guarded ranks enter the collective, the rest never
# do. `broadcast_from` IS rank-aware internally (every rank calls it) —
# what TD008 flags is a rank-guarded CALL SITE, where some rank skips the
# call entirely.
COLLECTIVE_CALLS = {
    # jax.lax primitives
    "psum", "pmean", "pmin", "pmax", "all_gather", "all_to_all",
    "ppermute", "pshuffle", "psum_scatter", "pgather",
    # tpu_dist.comm.collectives / quantize wrappers
    "reduce_mean", "reduce_sum", "broadcast_from", "barrier",
    "host_allreduce_mean", "quantized_pmean_flat",
    # jax.experimental.multihost_utils host-level syncs
    "broadcast_one_to_all", "process_allgather", "sync_global_devices",
    "reached_preemption_sync_point",
}
# ...except these receivers/modules, where a same-named method is host
# bookkeeping, not a collective (e.g. ``Counter``-style .barrier attrs).
# Matched on the resolved dotted prefix when resolution succeeds.
COLLECTIVE_CALL_NONMODULES = ("threading.", "multiprocessing.")

# TD006: exception types a `pass`-only handler may swallow without comment —
# probe/cleanup idioms where absence IS the answer. Matched on the LAST
# dotted segment (so `queue.Empty` and a bare `Empty` both pass). Anything
# else (OSError and friends above all) needs a logged handler or an inline
# `# tpu-dist: ignore[TD006]` with the audit reason.
TD006_ALLOWED_SILENT = {
    "FileNotFoundError",
    "ImportError",
    "ModuleNotFoundError",
    "StopIteration",
    "Empty",           # queue.Empty poll loops
    "TimeoutExpired",  # subprocess poll-wait loops
    "TimeoutError",
}

# Version-fragile imports (TD004): module → names that must come from compat.
FRAGILE_IMPORTS = {
    "jax": {"shard_map"},
    "jax.experimental": {"shard_map", "pjit"},
    "jax.experimental.shard_map": {"*"},
    "jax.experimental.pjit": {"*"},
}
# The one module allowed to perform those imports.
COMPAT_MODULE_SUFFIX = "tpu_dist/comm/compat.py"


def describe(rule_id: str) -> str:
    r = RULES.get(rule_id)
    return f"{r.id} ({r.name}): {r.summary}" if r else rule_id
