"""CLI: ``python -m tpu_dist.analysis`` — lint + jaxpr audit, gate-ready.

Exit codes: 0 clean (after suppressions + baseline), 1 violations,
2 internal error. ``--format json`` emits one machine-readable object for
the CI gate; text mode prints ``file:line:col: TDxxx message`` lines.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# The jaxpr layer traces shard_map programs, which need a multi-device
# mesh: force the 8-device emulated CPU backend BEFORE jax initializes
# (same mechanism as tests/conftest.py).
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

from tpu_dist.analysis import baseline as baseline_lib  # noqa: E402
from tpu_dist.analysis.lint import lint_paths  # noqa: E402
from tpu_dist.analysis.rules import RULES  # noqa: E402

DEFAULT_BASELINE = "tools/analysis_baseline.json"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpu_dist.analysis",
        description="distributed-training lint (TD0xx) + jaxpr audit (TD1xx)",
    )
    ap.add_argument(
        "paths",
        nargs="*",
        default=["tpu_dist"],
        help="files/dirs to lint (default: tpu_dist)",
    )
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument(
        "--baseline",
        default=None,
        help=f"baseline file (default: {DEFAULT_BASELINE} when it exists)",
    )
    ap.add_argument(
        "--write-baseline",
        action="store_true",
        help="accept current findings into the baseline file and exit 0",
    )
    ap.add_argument("--no-lint", action="store_true", help="skip the AST lint layer")
    ap.add_argument(
        "--no-jaxpr", action="store_true", help="skip the jaxpr audit layer"
    )
    ap.add_argument(
        "--case",
        action="append",
        help="run only this jaxpr audit case (repeatable)",
    )
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in RULES.values():
            print(f"{r.id}  {r.name}\n      {r.summary}")
        return 0

    violations = []
    report: dict = {}
    if not args.no_lint:
        try:
            violations.extend(lint_paths(args.paths))
        except FileNotFoundError as e:
            print(f"tpu_dist.analysis: {e}", file=sys.stderr)
            return 2
    if not args.no_jaxpr:
        import jax

        jax.config.update("jax_platforms", "cpu")
        from tpu_dist.analysis.jaxpr_audit import audit_all, registered_cases

        if args.case:
            unknown = sorted(set(args.case) - set(registered_cases()))
            if unknown:
                print(
                    f"tpu_dist.analysis: unknown audit case(s) {unknown}; "
                    f"registered: {registered_cases()}",
                    file=sys.stderr,
                )
                return 2
        jaxpr_report, jaxpr_violations = audit_all(names=args.case)
        report["jaxpr"] = jaxpr_report
        violations.extend(jaxpr_violations)

    baseline_path = args.baseline or (
        DEFAULT_BASELINE if os.path.exists(DEFAULT_BASELINE) else None
    )
    if args.write_baseline:
        if args.no_lint or args.no_jaxpr or args.case or args.paths != ["tpu_dist"]:
            # a partial run would REPLACE the file with only this run's
            # findings, silently dropping accepted entries from the layers
            # or paths that did not execute
            print(
                "tpu_dist.analysis: refusing --write-baseline on a partial "
                "run (--no-lint/--no-jaxpr/--case/custom paths); run the "
                "full analyzer to regenerate the baseline",
                file=sys.stderr,
            )
            return 2
        path = args.baseline or DEFAULT_BASELINE
        baseline_lib.write(violations, path)
        print(f"wrote {len(violations)} accepted finding(s) to {path}")
        return 0

    stale: list = []
    if baseline_path:
        violations, stale = baseline_lib.apply(
            violations, baseline_lib.load(baseline_path)
        )

    if args.format == "json":
        out = {
            "violations": [v.to_json() for v in violations],
            "stale_baseline_entries": stale,
            "jaxpr_report": report.get("jaxpr", {}),
            "counts": {"new": len(violations), "stale_baseline": len(stale)},
        }
        print(json.dumps(out, indent=2))
    else:
        for v in violations:
            print(v.format_text())
        for e in stale:
            print(
                f"stale baseline entry (no longer produced): "
                f"{e.get('rule')} {e.get('path')} {e.get('snippet')!r}"
            )
        n = len(violations)
        print(
            f"tpu_dist.analysis: {n} new violation(s)"
            + (f", {len(stale)} stale baseline entr(ies)" if stale else "")
        )
    return 1 if violations else 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except SystemExit:
        raise
    except BrokenPipeError:
        sys.exit(0)  # output piped into head etc.
    except BaseException:  # noqa: BLE001 — exit 2 distinguishes tool crashes
        import traceback

        traceback.print_exc()
        sys.exit(2)
