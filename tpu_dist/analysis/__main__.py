"""CLI: ``python -m tpu_dist.analysis`` — lint + jaxpr audit, gate-ready.

Exit codes: 0 clean (after suppressions + baseline), 1 violations,
2 internal error. ``--format json`` emits one machine-readable object for
the CI gate (including the full rule registry, the same source of truth
docs/analysis.md's rule table is tested against); text mode prints
``file:line:col: TDxxx message`` lines.

``python -m tpu_dist.analysis shard`` runs Layer 3 — the static HLO
sharding & collective audit (TD116/TD117) — and writes/prints the
``shard_report.json`` planner input (docs/shard_report.md).

``python -m tpu_dist.analysis plan`` runs Layer 4 — the static
``--auto_shard`` planner: enumerate + price + HBM-filter + rank the
config families, TD118-verify the chosen plan against a fresh compile,
and write the schema-pinned ``plan_report.json`` (docs/planner.md).

``python -m tpu_dist.analysis tune-overlap`` runs Layer 4b — the
comm/compute overlap autotuner: search the collective-scheduling knobs
(pmean_fusion, quant_chunk, rs_ag_chunks), TD121-gate every candidate
(payload bytes pinned, schedule must move), and write the schema-pinned
``tune_report.json`` the planner/trainer consume (docs/analysis.md).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# The jaxpr layer traces shard_map programs, which need a multi-device
# mesh: force the 8-device emulated CPU backend BEFORE jax initializes
# (same mechanism as tests/conftest.py).
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

from tpu_dist.analysis import baseline as baseline_lib  # noqa: E402
from tpu_dist.analysis.lint import lint_paths  # noqa: E402
from tpu_dist.analysis.rules import RULES  # noqa: E402

DEFAULT_BASELINE = "tools/analysis_baseline.json"


def shard_main(argv) -> int:
    """The ``shard`` subcommand: lower + compile every config family,
    audit the optimized HLO (TD116/TD117), emit the shard report."""
    ap = argparse.ArgumentParser(
        prog="python -m tpu_dist.analysis shard",
        description="static HLO sharding & collective audit (TD116/TD117) "
        "— writes the shard_report.json the --auto_shard planner reads",
    )
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument(
        "--out", default=None,
        help="write the schema-pinned shard_report.json here",
    )
    ap.add_argument(
        "--family", action="append",
        help="analyze only this config family (repeatable)",
    )
    ap.add_argument("--list-families", action="store_true")
    ap.add_argument(
        "--inject-reshard", action="store_true",
        help="ALSO analyze the deliberately mis-sharded ZeRO-1 probe "
        "(bad in_shardings) — its TD117 findings are expected and prove "
        "the detector is alive; exit 2 if it comes back clean",
    )
    args = ap.parse_args(argv)

    import jax

    jax.config.update("jax_platforms", "cpu")
    from tpu_dist.analysis import shardlint
    from tpu_dist.comm import mesh as mesh_lib

    if args.list_families:
        for name in shardlint.registered_families():
            print(name)
        return 0
    unknown = sorted(
        set(args.family or ()) - set(shardlint.registered_families())
    )
    if unknown:
        print(
            f"tpu_dist.analysis shard: unknown famil(ies) {unknown}; "
            f"registered: {shardlint.registered_families()}",
            file=sys.stderr,
        )
        return 2
    report, violations = shardlint.build_shard_report(names=args.family)
    if args.inject_reshard:
        inj = shardlint.injected_bad_zero1(mesh_lib.data_parallel_mesh())
        inj_report, inj_vs = shardlint.shard_case(
            "zero1_sgd", step_override=inj
        )
        report["injected_reshard_probe"] = {
            "violations": [v.to_json() for v in inj_vs],
            "caught": bool(inj_vs),
        }
        if not inj_vs:
            print(
                "tpu_dist.analysis shard: the injected bad-in_shardings "
                "probe came back CLEAN — the TD117 detector is dead",
                file=sys.stderr,
            )
            return 2
    if args.out:
        shardlint.save_shard_report(report, args.out)
    if args.format == "json":
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        print(shardlint.format_text(report))
        for v in violations:
            print(v.format_text())
        if args.out:
            print(f"shardlint: wrote {args.out}")
    if report["counts"]["skipped"] and not args.family:
        # a full run that silently skipped families must be loud (the
        # robustness contract: degrade per family, fail the gate overall)
        print(
            f"tpu_dist.analysis shard: {report['counts']['skipped']} "
            f"famil(ies) skipped: {report['skips']}",
            file=sys.stderr,
        )
        return 2
    return 1 if violations else 0


def plan_main(argv) -> int:
    """The ``plan`` subcommand: the static ``--auto_shard`` planner —
    enumerate, price, HBM-filter, rank, TD118-verify, emit the plan."""
    ap = argparse.ArgumentParser(
        prog="python -m tpu_dist.analysis plan",
        description="static --auto_shard planner: rank the config "
        "families by calibrated predicted step time under the per-chip "
        "HBM budget, TD118-verify the chosen plan, write plan_report.json",
    )
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument(
        "--out", default=None,
        help="write the schema-pinned plan_report.json here",
    )
    ap.add_argument(
        "--family", action="append",
        help="restrict the search to this config family (repeatable)",
    )
    ap.add_argument("--list-families", action="store_true")
    ap.add_argument(
        "--from-report", default=None, metavar="SHARD_REPORT",
        help="price candidates from an existing shard_report.json "
        "instead of recompiling each family (the TD118 verification "
        "still compiles the chosen family fresh)",
    )
    ap.add_argument(
        "--tune-report", default=None, metavar="TUNE_REPORT",
        help="tune_report.json from `tune-overlap`: attach the tuner's "
        "chosen schedule knobs to every candidate (tune_knobs) — knobs "
        "never change the ranking (TD121: schedule-only transforms)",
    )
    ap.add_argument(
        "--hbm_budget_bytes", type=int, default=None,
        help="per-device HBM budget override (default: the chip table; "
        "unknown chips — CPU emulation — skip the feasibility filter)",
    )
    ap.add_argument(
        "--memory_headroom", type=float, default=0.9, metavar="FRAC",
        help="fraction of the budget the static ledger may claim",
    )
    ap.add_argument(
        "--inject-miscost", action="store_true",
        help="ALSO run TD118 over a deliberately mis-priced copy of the "
        "plan (perturbed wire bytes) — its violations are expected and "
        "prove the detector is alive; exit 2 if it comes back clean",
    )
    args = ap.parse_args(argv)

    import jax

    jax.config.update("jax_platforms", "cpu")
    from tpu_dist.analysis import planner, shardlint

    if args.list_families:
        for name in planner.plan_candidates(jax.device_count()):
            print(name)
        return 0
    unknown = sorted(
        set(args.family or ()) - set(shardlint.registered_families())
    )
    if unknown:
        print(
            f"tpu_dist.analysis plan: unknown famil(ies) {unknown}; "
            f"registered: {shardlint.registered_families()}",
            file=sys.stderr,
        )
        return 2
    shard_report = None
    if args.from_report:
        try:
            shard_report = shardlint.load_shard_report(args.from_report)
        except (OSError, ValueError) as e:
            print(f"tpu_dist.analysis plan: {e}", file=sys.stderr)
            return 2
    tune_report = None
    if args.tune_report:
        from tpu_dist.analysis import overlap as overlap_lib

        try:
            tune_report = overlap_lib.load_tune_report(args.tune_report)
        except (OSError, ValueError) as e:
            print(f"tpu_dist.analysis plan: {e}", file=sys.stderr)
            return 2
    plan = planner.build_plan(
        names=args.family,
        hbm_budget_bytes=args.hbm_budget_bytes,
        memory_headroom=args.memory_headroom,
        shard_report=shard_report,
        tune_report=tune_report,
    )
    probe, violations = planner.verify_plan(plan)
    plan["verification"] = probe
    if args.inject_miscost:
        inj_probe, inj_vs = planner.verify_plan(
            planner.inject_miscost(plan)
        )
        plan["injected_miscost_probe"] = {
            "violations": inj_probe.get("violations", []),
            "caught": bool(inj_vs),
        }
        if not inj_vs:
            print(
                "tpu_dist.analysis plan: the injected mis-priced plan "
                "came back CLEAN — the TD118 detector is dead",
                file=sys.stderr,
            )
            return 2
    if args.out:
        planner.save_plan_report(plan, args.out)
    if args.format == "json":
        print(json.dumps(plan, indent=2, sort_keys=True))
    else:
        print(planner.format_text(plan))
        for v in violations:
            print(v.format_text())
        if args.out:
            print(f"autoplan: wrote {args.out}")
    if plan["counts"]["skipped"] and not args.family:
        # a full search that silently lost families must be loud (the
        # same degrade-per-family/fail-the-gate contract shard has)
        print(
            f"tpu_dist.analysis plan: {plan['counts']['skipped']} "
            f"famil(ies) skipped: {plan['skips']}",
            file=sys.stderr,
        )
        return 2
    return 1 if violations else 0


def tune_main(argv) -> int:
    """The ``tune-overlap`` subcommand: Layer 4b — search the
    collective-scheduling knobs, TD121-gate, emit tune_report.json."""
    ap = argparse.ArgumentParser(
        prog="python -m tpu_dist.analysis tune-overlap",
        description="comm/compute overlap autotuner: search the "
        "schedule-only collective knobs per config family (TD121-gated: "
        "payload bytes pinned, schedule must move), write tune_report.json",
    )
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument(
        "--out", default=None,
        help="write the schema-pinned tune_report.json here",
    )
    ap.add_argument(
        "--family", action="append",
        help="tune only this config family (repeatable)",
    )
    ap.add_argument("--list-families", action="store_true")
    ap.add_argument(
        "--capture", default=None, metavar="DIR",
        help="jax.profiler capture dir: use the measured overlap_frac "
        "as the objective instead of the HLO schedule proxy",
    )
    ap.add_argument(
        "--inject-payload", action="store_true",
        help="ALSO re-gate a deliberately payload-perturbed copy of the "
        "report — its TD121 findings are expected and prove the detector "
        "is alive; exit 2 if it comes back clean",
    )
    args = ap.parse_args(argv)

    import jax

    jax.config.update("jax_platforms", "cpu")
    from tpu_dist.analysis import overlap as overlap_lib

    if args.list_families:
        for name in overlap_lib.tunable_families():
            print(name)
        return 0
    unknown = sorted(
        set(args.family or ()) - set(overlap_lib.tunable_families())
    )
    if unknown:
        print(
            f"tpu_dist.analysis tune-overlap: unknown/untunable "
            f"famil(ies) {unknown}; tunable: "
            f"{overlap_lib.tunable_families()}",
            file=sys.stderr,
        )
        return 2
    report, violations = overlap_lib.tune(
        names=args.family, capture_dir=args.capture
    )
    if args.inject_payload:
        inj_vs = overlap_lib.recheck_report(
            overlap_lib.inject_payload(report)
        )
        report["injected_payload_probe"] = {
            "violations": [v.to_json() for v in inj_vs],
            "caught": bool(inj_vs),
        }
        if not inj_vs:
            print(
                "tpu_dist.analysis tune-overlap: the injected payload-"
                "perturbed report came back CLEAN — the TD121 detector "
                "is dead",
                file=sys.stderr,
            )
            return 2
    if args.out:
        overlap_lib.save_tune_report(report, args.out)
    if args.format == "json":
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        print(overlap_lib.format_text(report))
        for v in violations:
            print(v.format_text())
        if args.out:
            print(f"tune-overlap: wrote {args.out}")
    if report["counts"]["skipped"] and not args.family:
        # same degrade-per-family/fail-the-gate contract as shard/plan
        print(
            f"tpu_dist.analysis tune-overlap: "
            f"{report['counts']['skipped']} famil(ies) skipped: "
            f"{report['skips']}",
            file=sys.stderr,
        )
        return 2
    return 1 if violations else 0


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "shard":
        return shard_main(argv[1:])
    if argv and argv[0] == "plan":
        return plan_main(argv[1:])
    if argv and argv[0] == "tune-overlap":
        return tune_main(argv[1:])
    ap = argparse.ArgumentParser(
        prog="python -m tpu_dist.analysis",
        description="distributed-training lint (TD0xx) + jaxpr audit (TD1xx)",
    )
    ap.add_argument(
        "paths",
        nargs="*",
        default=["tpu_dist"],
        help="files/dirs to lint (default: tpu_dist)",
    )
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument(
        "--baseline",
        default=None,
        help=f"baseline file (default: {DEFAULT_BASELINE} when it exists)",
    )
    ap.add_argument(
        "--write-baseline",
        action="store_true",
        help="accept current findings into the baseline file and exit 0",
    )
    ap.add_argument("--no-lint", action="store_true", help="skip the AST lint layer")
    ap.add_argument(
        "--no-jaxpr", action="store_true", help="skip the jaxpr audit layer"
    )
    ap.add_argument(
        "--case",
        action="append",
        help="run only this jaxpr audit case (repeatable)",
    )
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in sorted(RULES.values(), key=lambda r: r.id):
            print(f"{r.id}  {r.name}\n      {r.summary}")
        return 0

    violations = []
    report: dict = {}
    if not args.no_lint:
        try:
            violations.extend(lint_paths(args.paths))
        except FileNotFoundError as e:
            print(f"tpu_dist.analysis: {e}", file=sys.stderr)
            return 2
    if not args.no_jaxpr:
        import jax

        jax.config.update("jax_platforms", "cpu")
        from tpu_dist.analysis.jaxpr_audit import audit_all, registered_cases

        if args.case:
            unknown = sorted(set(args.case) - set(registered_cases()))
            if unknown:
                print(
                    f"tpu_dist.analysis: unknown audit case(s) {unknown}; "
                    f"registered: {registered_cases()}",
                    file=sys.stderr,
                )
                return 2
        jaxpr_report, jaxpr_violations = audit_all(names=args.case)
        report["jaxpr"] = jaxpr_report
        violations.extend(jaxpr_violations)

    baseline_path = args.baseline or (
        DEFAULT_BASELINE if os.path.exists(DEFAULT_BASELINE) else None
    )
    if args.write_baseline:
        if args.no_lint or args.no_jaxpr or args.case or args.paths != ["tpu_dist"]:
            # a partial run would REPLACE the file with only this run's
            # findings, silently dropping accepted entries from the layers
            # or paths that did not execute
            print(
                "tpu_dist.analysis: refusing --write-baseline on a partial "
                "run (--no-lint/--no-jaxpr/--case/custom paths); run the "
                "full analyzer to regenerate the baseline",
                file=sys.stderr,
            )
            return 2
        path = args.baseline or DEFAULT_BASELINE
        baseline_lib.write(violations, path)
        print(f"wrote {len(violations)} accepted finding(s) to {path}")
        return 0

    stale: list = []
    if baseline_path:
        violations, stale = baseline_lib.apply(
            violations, baseline_lib.load(baseline_path)
        )

    if args.format == "json":
        out = {
            "violations": [v.to_json() for v in violations],
            "stale_baseline_entries": stale,
            "jaxpr_report": report.get("jaxpr", {}),
            "counts": {"new": len(violations), "stale_baseline": len(stale)},
            # the FULL rule registry, in one machine-readable place — the
            # same source of truth docs/analysis.md's rule table is tested
            # against (tests/test_shardlint.py), so a rule cannot land
            # half-registered
            "rules": [
                {"id": r.id, "name": r.name, "summary": r.summary}
                for r in sorted(RULES.values(), key=lambda r: r.id)
            ],
        }
        print(json.dumps(out, indent=2))
    else:
        for v in violations:
            print(v.format_text())
        for e in stale:
            print(
                f"stale baseline entry (no longer produced): "
                f"{e.get('rule')} {e.get('path')} {e.get('snippet')!r}"
            )
        n = len(violations)
        print(
            f"tpu_dist.analysis: {n} new violation(s)"
            + (f", {len(stale)} stale baseline entr(ies)" if stale else "")
        )
    return 1 if violations else 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except SystemExit:
        raise
    except BrokenPipeError:
        sys.exit(0)  # output piped into head etc.
    except BaseException:  # noqa: BLE001 — exit 2 distinguishes tool crashes
        import traceback

        traceback.print_exc()
        sys.exit(2)
