"""Comm/compute overlap autotuner (Layer 4b, ``tune-overlap``) — search
the collective-*scheduling* knobs the step builders already expose and
pick, per config family, the setting that maximizes the measured overlap
headroom. The knobs move WHEN collectives run, never WHAT they carry:

- ``pmean_fusion`` (``dp_sgd``): one fused multi-operand grad pmean vs
  one pmean per gradient leaf — same payload bytes, many small
  collectives the scheduler can launch as each leaf's backward finishes.
- ``quant_chunk`` (``dp_int8`` / ``dp_int8_ef``): the int8 wire's
  quantization-block size — payload bytes identical, only the f32 scale
  sideband (and the chunking of the two all-to-all legs) changes.
- ``rs_ag_chunks`` (``zero1_sgd``): split the ZeRO-1 reduce-scatter /
  all-gather pair into k pipelined column-group collectives — the groups
  tile the padded extent exactly, so not one wire byte is added.

TD121 pins that contract mechanically, per candidate: the shardlint
payload bucket (``hlo_wire_buckets``) must be byte-identical to the
family's baseline, and the schedule metric must MOVE (a knob that
changes nothing is a lying search space). The ``--inject-payload`` probe
perturbs a recorded payload and requires the detector to fire — clean
means the detector is dead, CLI exit 2, the same acceptance discipline
as the planner's ``--inject-miscost`` (TD118).

Overlap measurement: with a profiler capture (``jax.profiler`` +
``obs/xprof.py``) the real ``overlap_frac`` is the objective. While the
TPU tunnel is down the CPU-valid proxy is the compiled-HLO *scheduling
distance* — for every collective, how many instructions sit between it
and its first consumer in the optimized module. XLA's async pairs make
this literal (the ``-start``→``-done`` gap IS the overlap window); for
sync ops it measures how much independent work the scheduler placed
behind the op. Deterministic, pure-compile, no devices harmed.

The emitted ``tune_report.json`` (schema ``tune_report_v1``) is consumed
by the ``--auto_shard`` planner (``planner.build_plan(tune_report=...)``)
which attaches the chosen knobs to its chosen family, and by the trainer,
which applies them and exports ``tune.*`` gauges into history.
"""

from __future__ import annotations

import copy
import json
import re
from typing import Optional

from tpu_dist.analysis.rules import Violation

SCHEMA = "tune_report_v1"
SCHEMA_VERSION = 1
_SCHEMA_RE = re.compile(r"^tune_report_v(\d+)$")


class TuneReportError(ValueError):
    """A tune_report.json failed schema validation on load."""


# --------------------------------------------------------------------------
# The knob space. Baseline ({}) first — every candidate is judged against
# it. Values are make_train_step kwargs, plain data (serializable).
# --------------------------------------------------------------------------

#: The quant_chunk values are sized to the audit proxy model (the
#: _AuditMLP's per-replica row is 480/8 = 60 elements): every searched
#: value must change the scale-sideband granularity ON THE PROXY or the
#: TD121 moved-gate correctly flags it as vacuous. The report records
#: what was searched — consumers apply the chosen VALUE, and a family
#: whose baseline wins simply ships no override.
KNOB_SPACE: dict = {
    "dp_sgd": [{}, {"pmean_fusion": "per_leaf"}],
    "dp_int8": [{}, {"quant_chunk": 16}, {"quant_chunk": 32}],
    "dp_int8_ef": [{}, {"quant_chunk": 16}, {"quant_chunk": 32}],
    "zero1_sgd": [{}, {"rs_ag_chunks": 2}, {"rs_ag_chunks": 4}],
}


def tunable_families() -> list:
    return sorted(KNOB_SPACE)


# --------------------------------------------------------------------------
# The schedule metric (the CPU-valid overlap proxy)
# --------------------------------------------------------------------------

_DEF_RE = re.compile(r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=")
_COLLECTIVE_DEF_RE = re.compile(
    r"=\s*.*?\s(?:all-reduce|all-gather|reduce-scatter|all-to-all|"
    r"collective-permute)(?:-start)?\("
)


def schedule_distances(hlo_text: str) -> list:
    """Per-collective first-consumer distances from optimized HLO text.

    For every collective definition (sync op or async ``-start``), the
    number of instruction lines between it and the first later line in
    the same computation that references its result. ``-done`` ops are
    not collectives of their own — they ARE the consumer that closes a
    ``-start``'s window. A collective whose result is never referenced
    again in its computation (it is the ROOT) scores the distance to the
    computation's end — nothing can be scheduled behind it.

    Returns ``[{"computation", "line", "kind", "distance"}, ...]`` in
    module order. Deterministic for a fixed compile."""
    from tpu_dist.analysis.shardlint import _KIND_RE, _split_computations

    out = []
    for comp, lines in _split_computations(hlo_text).items():
        for i, line in enumerate(lines):
            m = _KIND_RE.search(line)
            if not m or m.group(3) == "-done":
                continue
            d = _DEF_RE.match(line)
            if not d:
                continue
            name = d.group(1)
            # %name followed by a non-identifier char, so %ar.1 does not
            # match inside %ar.12
            use_re = re.compile(r"%" + re.escape(name) + r"(?![\w.\-])")
            distance = len(lines) - 1 - i  # ROOT / never-consumed default
            for j in range(i + 1, len(lines)):
                if use_re.search(lines[j]):
                    distance = j - i
                    break
            out.append({
                "computation": comp,
                "line": i,
                "kind": m.group(2) + (m.group(3) or ""),
                "distance": distance,
            })
    return out


def schedule_metric(hlo_text: str) -> dict:
    """Aggregate :func:`schedule_distances` into the tuner's objective:
    ``mean_distance`` (higher = more independent work the scheduler
    placed behind each collective = more overlap headroom)."""
    ds = schedule_distances(hlo_text)
    n = len(ds)
    total = sum(d["distance"] for d in ds)
    return {
        "collectives": n,
        "total_distance": total,
        "mean_distance": (total / n) if n else 0.0,
        "min_distance": min((d["distance"] for d in ds), default=0),
        "per_op": ds,
    }


def overlap_frac_from_capture(capture_dir: str) -> Optional[float]:
    """Measured comm/compute ``overlap_frac`` from a ``jax.profiler``
    capture (``obs/xprof.py``) — the objective when real device traces
    exist. Returns None when the capture is unreadable (the caller falls
    back to the HLO schedule proxy, counted in the report)."""
    try:
        from tpu_dist.obs import xprof as xprof_lib

        report = xprof_lib.analyze_capture(capture_dir)
        return float(report["overlap"]["overlap_frac"])
    except Exception:
        return None


# --------------------------------------------------------------------------
# Candidate compilation + measurement
# --------------------------------------------------------------------------


def compile_candidate(family: str, knobs: dict, mesh=None) -> dict:
    """Build the family's step with ``knobs`` overriding its
    :func:`family_step_kwargs`, compile it, and measure: the shardlint
    payload/sideband wire buckets (the TD121-pinned inventory) plus the
    schedule metric. Pure compile — nothing executes."""
    from tpu_dist.analysis.jaxpr_audit import _dp_setup
    from tpu_dist.analysis.shardlint import (
        hlo_wire_buckets,
        parse_hlo_collectives,
    )
    from tpu_dist.comm import mesh as mesh_lib
    from tpu_dist.obs import costmodel
    from tpu_dist.train.step import family_step_kwargs

    from tpu_dist.analysis.jaxpr_audit import trace_counts

    m = mesh if mesh is not None else mesh_lib.data_parallel_mesh()
    kwargs = dict(family_step_kwargs(family))
    kwargs.update(knobs)
    step, args = _dp_setup(m, **kwargs)
    _, compiled = costmodel.lower_and_compile(step, *args)
    text = compiled.as_text()
    ops = parse_hlo_collectives(text)
    metric = schedule_metric(text)
    distances = [d["distance"] for d in metric.pop("per_op")]
    # the jaxpr collective-eqn count is part of the schedule fingerprint:
    # fused-vs-per-leaf pmean compiles to identical CPU HLO (XLA splits
    # the multi-operand reduce either way), but the ISSUED granularity —
    # what the TPU all-reduce combiner and latency-hiding scheduler
    # actually receive — is the eqn structure, and the knob must move it
    jaxpr_colls = sum(trace_counts(step, *args)["collectives"].values())
    return {
        "family": family,
        "knobs": dict(knobs),
        "wire": hlo_wire_buckets(ops),
        "collective_ops": len(ops),
        "jaxpr_collectives": int(jaxpr_colls),
        "fingerprint": [[op.kind, op.dtype, op.elems] for op in ops],
        "distances": distances,
        "schedule": metric,
    }


def _payload_key(entry: dict) -> tuple:
    w = entry.get("wire") or {}
    return (
        int(w.get("payload_bytes", -1)),
        int(w.get("quantized_payload_bytes", -1)),
    )


def check_candidate(
    family: str, baseline: dict, cand: dict
) -> list[Violation]:
    """The TD121 gate for one measured candidate against its family
    baseline: payload bucket byte-identical, schedule metric moved."""
    out: list[Violation] = []
    if not cand.get("knobs"):
        return out  # the baseline is its own reference
    where = f"<tune:{family}:{json.dumps(cand['knobs'], sort_keys=True)}>"
    if _payload_key(cand) != _payload_key(baseline):
        out.append(Violation(
            rule="TD121", path=where, line=0,
            message=(
                "knob changed the payload-byte inventory: baseline "
                f"payload={baseline.get('wire', {}).get('payload')} vs "
                f"candidate payload={cand.get('wire', {}).get('payload')} "
                "— tuner knobs must be schedule-only transforms"
            ),
        ))
    moved = (
        cand.get("fingerprint") != baseline.get("fingerprint")
        or cand.get("distances") != baseline.get("distances")
        or cand.get("jaxpr_collectives") != baseline.get("jaxpr_collectives")
    )
    if not moved:
        out.append(Violation(
            rule="TD121", path=where, line=0,
            message=(
                "knob did not move the collective schedule (identical "
                "HLO op sequence, first-consumer distances, and jaxpr "
                "collective-eqn structure) — a vacuous knob poisons "
                "the search space"
            ),
        ))
    return out


# --------------------------------------------------------------------------
# The search
# --------------------------------------------------------------------------


def tune(
    mesh=None, names=None, capture_dir: Optional[str] = None
) -> tuple[dict, list[Violation]]:
    """Compile every candidate in :data:`KNOB_SPACE` (restricted to
    ``names`` when given), gate each through TD121, and choose per
    family the TD121-clean candidate with the highest objective —
    measured ``overlap_frac`` when ``capture_dir`` yields one, the HLO
    schedule proxy otherwise. Build/compile failures are counted in
    ``skips``, never silent (a skipped family is CLI exit 2)."""
    import jax

    from tpu_dist.comm import mesh as mesh_lib

    m = mesh if mesh is not None else mesh_lib.data_parallel_mesh()
    fams = list(names) if names else tunable_families()
    measured_frac = (
        overlap_frac_from_capture(capture_dir) if capture_dir else None
    )
    families: dict = {}
    skips: dict = {}
    violations: list[Violation] = []
    for fam in fams:
        if fam not in KNOB_SPACE:
            skips[fam] = (
                f"no tunable knobs registered; tunable: {tunable_families()}"
            )
            continue
        space = KNOB_SPACE[fam]
        try:
            baseline = compile_candidate(fam, space[0], m)
        except Exception as e:
            skips[fam] = f"{type(e).__name__}: {e}"
            continue
        cands = [baseline]
        for knobs in space[1:]:
            try:
                cand = compile_candidate(fam, knobs, m)
            except Exception as e:
                skips[f"{fam}:{json.dumps(knobs, sort_keys=True)}"] = (
                    f"{type(e).__name__}: {e}"
                )
                continue
            vs = check_candidate(fam, baseline, cand)
            cand["td121"] = {
                "clean": not vs,
                "violations": [v.to_json() for v in vs],
            }
            violations.extend(vs)
            cands.append(cand)
        # deterministic choice: highest mean first-consumer distance
        # among TD121-clean candidates; the serialized knobs break exact
        # ties (never dict order)
        eligible = [
            c for c in cands
            if not c.get("knobs") or c.get("td121", {}).get("clean")
        ]
        chosen = max(
            eligible,
            key=lambda c: (
                c["schedule"]["mean_distance"],
                json.dumps(c["knobs"], sort_keys=True),
            ),
        )
        families[fam] = {
            "baseline": baseline,
            "candidates": cands,
            "chosen": {
                "knobs": chosen["knobs"],
                "schedule": chosen["schedule"],
                "gain_frac": (
                    chosen["schedule"]["mean_distance"]
                    / baseline["schedule"]["mean_distance"] - 1.0
                    if baseline["schedule"]["mean_distance"] else 0.0
                ),
            },
        }
    dev = jax.devices()[0]
    report = {
        "schema": SCHEMA,
        "backend": dev.platform,
        "device_kind": dev.device_kind,
        "n_devices": int(m.devices.size),
        "jax_version": jax.__version__,
        "objective": (
            "xprof_overlap_frac" if measured_frac is not None
            else "hlo_schedule_proxy"
        ),
        "measured_overlap_frac": measured_frac,
        "families": families,
        "skips": skips,
        "counts": {
            "families": len(families),
            "skipped": len(skips),
            "violations": len(violations),
        },
    }
    return report, violations


def chosen_knobs(report: dict, family: str) -> dict:
    """The tuner's chosen knob dict for ``family`` (``{}`` when the
    family was not tuned / the baseline won) — the planner/trainer
    consumption hook."""
    entry = (report.get("families") or {}).get(family) or {}
    return dict((entry.get("chosen") or {}).get("knobs") or {})


# --------------------------------------------------------------------------
# TD121 acceptance probe
# --------------------------------------------------------------------------


def inject_payload(report: dict) -> dict:
    """The TD121 acceptance probe (the planner's ``inject_miscost``
    twin): a deep copy of ``report`` where every non-baseline
    candidate's recorded payload bytes are deterministically perturbed
    (doubled + 1). :func:`recheck_report` over the result MUST flag
    TD121 — a clean verdict means the detector is dead (CLI exit 2)."""
    out = copy.deepcopy(report)
    for entry in (out.get("families") or {}).values():
        for cand in entry.get("candidates") or []:
            if not cand.get("knobs"):
                continue
            w = cand.setdefault("wire", {})
            w["payload_bytes"] = int(w.get("payload_bytes", 0)) * 2 + 1
    return out


def recheck_report(report: dict) -> list[Violation]:
    """Re-run the TD121 gate over a report's RECORDED inventories (no
    recompile — this is the probe verifier and the cheap CI re-gate)."""
    out: list[Violation] = []
    for fam, entry in (report.get("families") or {}).items():
        baseline = entry.get("baseline") or {}
        for cand in entry.get("candidates") or []:
            out.extend(check_candidate(fam, baseline, cand))
    return out


# --------------------------------------------------------------------------
# tune_report.json — save / load (forward-compat), rendering
# --------------------------------------------------------------------------

_REQUIRED_CHOSEN_KEYS = ("knobs", "schedule")


def save_tune_report(report: dict, path: str) -> None:
    import os

    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(report, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)


def load_tune_report(path: str) -> dict:
    """Schema-pinned loader with the planner's forward-compat
    discipline: the tag must parse as ``tune_report_v<N>``; a NEWER
    version is tolerated — family entries missing the v1 keys are
    skipped with a count into ``load_notes`` — while a foreign tag, an
    older-than-supported version, or a same-version entry missing
    required keys raises the typed :class:`TuneReportError`."""
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if not isinstance(data, dict):
        raise TuneReportError(f"{path}: not a JSON object")
    tag = data.get("schema")
    m = _SCHEMA_RE.match(tag) if isinstance(tag, str) else None
    if not m:
        raise TuneReportError(
            f"{path}: schema {tag!r} is not a tune_report tag — "
            "regenerate with `make tune-overlap`"
        )
    ver = int(m.group(1))
    if ver < SCHEMA_VERSION:
        raise TuneReportError(
            f"{path}: schema {tag!r} predates v{SCHEMA_VERSION} — "
            "regenerate with `make tune-overlap`"
        )
    newer = ver > SCHEMA_VERSION
    fams = data.get("families")
    if not isinstance(fams, dict):
        raise TuneReportError(f"{path}: no 'families' mapping")
    skipped: dict = {}
    kept: dict = {}
    for fam, entry in fams.items():
        chosen = entry.get("chosen") if isinstance(entry, dict) else None
        missing = (
            [k for k in _REQUIRED_CHOSEN_KEYS if k not in chosen]
            if isinstance(chosen, dict) else list(_REQUIRED_CHOSEN_KEYS)
        )
        if not missing:
            kept[fam] = entry
            continue
        if not newer:
            raise TuneReportError(
                f"{path}: family {fam!r} chosen entry is missing {missing}"
            )
        skipped[fam] = missing
    data["families"] = kept
    if newer:
        data["load_notes"] = {
            "newer_schema": tag,
            "reader_version": SCHEMA_VERSION,
            "skipped_families": skipped,
            "skipped_count": len(skipped),
        }
    return data


def format_text(report: dict) -> str:
    lines = [
        f"tune-overlap [{report.get('schema')}] "
        f"backend={report.get('backend')} "
        f"n_devices={report.get('n_devices')} "
        f"objective={report.get('objective')}",
    ]
    for fam, entry in sorted((report.get("families") or {}).items()):
        chosen = entry.get("chosen") or {}
        base = (entry.get("baseline") or {}).get("schedule") or {}
        lines.append(
            f"  {fam}: chosen={json.dumps(chosen.get('knobs'), sort_keys=True)} "
            f"mean_dist {base.get('mean_distance', 0):.2f} -> "
            f"{(chosen.get('schedule') or {}).get('mean_distance', 0):.2f} "
            f"({chosen.get('gain_frac', 0.0):+.1%})"
        )
        for cand in entry.get("candidates") or []:
            if not cand.get("knobs"):
                continue
            td = cand.get("td121") or {}
            tag = "ok" if td.get("clean") else "TD121-VIOLATION"
            lines.append(
                f"    cand {json.dumps(cand['knobs'], sort_keys=True)}: "
                f"mean_dist={cand['schedule']['mean_distance']:.2f} "
                f"payload={_payload_key(cand)[0]}B [{tag}]"
            )
    for key, why in sorted((report.get("skips") or {}).items()):
        lines.append(f"  SKIP {key}: {why}")
    c = report.get("counts") or {}
    lines.append(
        f"  families={c.get('families')} skipped={c.get('skipped')} "
        f"violations={c.get('violations')}"
    )
    return "\n".join(lines)
