"""Layer 4 — the static ``--auto_shard`` planner (``autoplan``).

shardlint (Layer 3) *audits* what GSPMD emitted per config family; this
layer *searches* over those families. The plan loop is:

1. **Enumerate** the feasible config space from the ONE registry the
   analyzers already walk (``train/step.py::SHARD_CONFIG_FAMILIES`` via
   the shardlint family builders) — dp × zero1 × grad-compression modes
   plus the tp/sp mesh layouts, filtered by each family's
   ``min_devices`` against the available device count. Every candidate
   is therefore a program shardlint knows how to compile and audit.
2. **Price** each candidate with :func:`costmodel.predicted_step_time`:
   XLA's per-step FLOPs/bytes corrected by the measured
   ``cost.calibration_*`` gauges (uncalibrated defaults when no capture
   ever ran — deterministic, and stamped as such), plus the TD104/HLO
   ring-model wire bytes of the family's compiled collectives.
3. **Filter** against the PR 13 per-chip static HBM ledger through the
   SAME refusal path ``--memory_check refuse`` uses
   (:func:`tpu_dist.obs.memory.preflight_check`): an infeasible
   candidate is refused with the typed
   :class:`~tpu_dist.obs.memory.InfeasibleMemoryError`, recorded
   skip-with-count — never silently dropped.
4. **Rank** deterministically (predicted step time, family-name
   tie-break; a pure function of its inputs — no wall clock anywhere)
   and emit the plan table + the chosen plan into a schema-pinned
   ``plan_report.json`` (:data:`SCHEMA`).

Two rules make the planner itself auditable:

* **TD118** ``plan-must-verify`` — :func:`verify_plan` recompiles the
  chosen family fresh through shardlint and requires the compiled HLO
  collective inventory (per-kind ops/elements/bytes and the total wire
  bytes) to match the inventory the planner priced byte-for-byte. A
  plan whose cost basis diverges from what GSPMD actually emits fails
  loudly; the ``--inject-miscost`` probe (:func:`inject_miscost`)
  perturbs the priced wire bytes and MUST be caught (the CLI exits 2
  when the detector comes back clean — a dead detector is worse than a
  bad plan).
* **TD119** ``planner-error-tracked`` — after any profiled run the
  trainer lands predicted-vs-achieved step time in history as
  ``planner_error_frac`` (a ``plan`` record, schema v12) and
  ``obs compare`` gates it through ``METRIC_DIRECTIONS`` (lower is
  better), so planner drift is a regression like any other.

Everything is host-side lowering/compiling for *text* — CPU-valid
evidence while the TPU tunnel is down, the same posture shardlint
established. docs/planner.md documents the search space, the pricing
model, and the plan_report schema.
"""

from __future__ import annotations

import copy
import json
import re
from typing import Optional

from tpu_dist.analysis.rules import Violation

SCHEMA = "plan_report_v1"
SCHEMA_VERSION = 1
_SCHEMA_RE = re.compile(r"^plan_report_v(\d+)$")


class PlanReportError(ValueError):
    """A plan_report.json failed schema validation on load."""


#: Uncalibrated pricing rates (FLOP/s and bytes/s per device, overlap
#: fraction) used when no ``cost.calibration_*`` capture has ever been
#: published — roughly a mid-range accelerator, but the absolute values
#: matter far less than the fact that they are FIXED: with one shared
#: rate pair the ranking reduces to the candidates' relative FLOP/byte/
#: wire volumes, and the whole plan stays a deterministic pure function
#: of its inputs (the search-determinism contract tests pin).
UNCALIBRATED_RATES = {
    "cost.calibration_flops_per_s": 1.0e12,
    "cost.calibration_bytes_per_s": 1.0e11,
    "cost.calibration_overlap_frac": 0.0,
}

#: Families ``--auto_shard apply`` may rewrite a TrainConfig to: the
#: flag overrides that select each family on the REAL model. tp/sp stay
#: plan-only (their way counts need model support — ``--tp``/``--sp``
#: remain explicit CLI decisions), and so does fsdp's GSPMD engine when
#: the current config already composes model axes.
FAMILY_TRAIN_OVERRIDES: dict = {
    "dp_sgd": {},
    "dp_sgd_accum4": {"grad_accu_steps": 4},
    "dp_bf16": {"bf16": True},
    "dp_wire_bf16": {"grad_compression": "bf16"},
    "dp_int8": {"grad_compression": "int8"},
    "dp_int8_ef": {"grad_compression": "int8_ef"},
    "zero1_sgd": {"shard_weight_update": True},
    "zero1_int8": {"shard_weight_update": True, "grad_compression": "int8"},
    "fsdp": {"fsdp": True},
}


def family_train_overrides(name: str) -> dict:
    """The :class:`TrainConfig` field overrides that apply family
    ``name`` to a real training run; raises ``KeyError`` with the
    applyable set for plan-only families (tp_vit/sp_vit)."""
    if name not in FAMILY_TRAIN_OVERRIDES:
        raise KeyError(
            f"family {name!r} is plan-only (not auto-applyable); "
            f"applyable: {sorted(FAMILY_TRAIN_OVERRIDES)}"
        )
    return dict(FAMILY_TRAIN_OVERRIDES[name])


def family_of(
    *,
    grad_compression: str = "none",
    bf16: bool = False,
    grad_accu_steps: int = 1,
    shard_weight_update: bool = False,
    fsdp: bool = False,
) -> Optional[str]:
    """The :data:`FAMILY_TRAIN_OVERRIDES` label of a flag combo — the
    inverse lookup bench.py uses to stamp which planner family a measured
    record corresponds to. ``None`` for combos outside the registry
    (e.g. bf16 compute + int8 wire together): an honest "no label" beats
    the nearest-match guess."""
    flags: dict = {}
    if grad_compression != "none":
        flags["grad_compression"] = grad_compression
    if bf16:
        flags["bf16"] = True
    if grad_accu_steps > 1:
        flags["grad_accu_steps"] = grad_accu_steps
    if shard_weight_update:
        flags["shard_weight_update"] = True
    if fsdp:
        flags["fsdp"] = True
    for name, overrides in FAMILY_TRAIN_OVERRIDES.items():
        if overrides == flags:
            return name
    return None


def pricing_gauges(gauges: Optional[dict] = None) -> tuple[dict, str]:
    """The rate gauges one plan prices every candidate with: the
    uncalibrated defaults, overlaid with any live ``cost.calibration_*``
    gauges (a capture ran), overlaid with ``gauges`` (tests / replaying
    a recorded calibration). Returns ``(gauges, source)`` where source
    is ``"calibrated"`` when any measured rate survived into the set —
    the report stamps it so a ranking priced on defaults can never be
    mistaken for a measured one."""
    from tpu_dist.obs import counters as counters_lib

    out = dict(UNCALIBRATED_RATES)
    source = "uncalibrated-defaults"
    live = {
        k: v for k, v in counters_lib.snapshot().items()
        if k.startswith("cost.calibration_")
    }
    for layer in (live, gauges or {}):
        for k, v in layer.items():
            if isinstance(v, (int, float)):
                out[k] = v
                if k in ("cost.calibration_flops_per_s",
                         "cost.calibration_bytes_per_s"):
                    source = "calibrated"
    return out, source


def plan_candidates(n_devices: int, names=None) -> list:
    """The search space: every registered *train*-kind shardlint family
    whose ``min_devices`` fits (serve families price a different
    objective and stay out). Deterministic order (sorted names)."""
    from tpu_dist.analysis import shardlint

    out = []
    for name in sorted(names if names is not None
                       else shardlint.registered_families()):
        fam = shardlint._FAMILIES.get(name)
        if fam is None or fam.kind != "train":
            continue
        if fam.min_devices > n_devices:
            continue
        out.append(name)
    return out


def priced_inventory_of(entry: dict) -> dict:
    """The TD118 basis extracted from one shard-report family entry: the
    per-kind compiled-collective counts the plan's price rests on."""
    by_kind = (entry.get("hlo") or {}).get("by_kind") or {}
    return {
        kind: {
            "ops": int(e.get("ops", 0)),
            "elems": int(e.get("elems", 0)),
            "bytes": int(e.get("bytes", 0)),
        }
        for kind, e in sorted(by_kind.items())
    }


def price_candidate(
    name: str, entry: dict, *, n_devices: int, gauges: dict,
) -> dict:
    """One ranked-table row from a shard-report family entry: the
    calibrated step-time prediction over the entry's XLA cost + HLO
    ring-model wire bytes, plus the static HBM requirement and the
    priced collective inventory TD118 later verifies."""
    from tpu_dist.obs import costmodel

    hlo = entry.get("hlo") or {}
    wire_bytes = hlo.get("bytes")
    cost = entry.get("cost") or {}
    predicted = costmodel.predicted_step_time(
        cost, wire_bytes=wire_bytes, n_devices=n_devices, gauges=gauges,
    )
    hbm = entry.get("hbm") or {}
    return {
        "family": name,
        "mesh": entry.get("mesh"),
        "config": entry.get("config"),
        "note": entry.get("note", ""),
        "wire_bytes": wire_bytes,
        "cost": {
            "flops_per_step": cost.get("flops_per_step"),
            "bytes_per_step": cost.get("bytes_per_step"),
        },
        "static_bytes_per_device": hbm.get("static_bytes_per_device"),
        "predicted": predicted,
        "predicted_step_s": predicted.get("predicted_step_s"),
        "priced_inventory": priced_inventory_of(entry),
        "applyable": name in FAMILY_TRAIN_OVERRIDES,
    }


def build_plan(
    *,
    mesh=None,
    names=None,
    hbm_budget_bytes: Optional[int] = None,
    memory_headroom: float = 0.9,
    gauges: Optional[dict] = None,
    shard_report: Optional[dict] = None,
    applyable_only: bool = False,
    tune_report: Optional[dict] = None,
) -> dict:
    """Search the family space and return the schema-pinned plan report.

    ``shard_report``: a loaded ``shard_report.json`` dict — candidates
    are priced from its family entries instead of recompiling (the
    ``--from-report`` path). ``gauges`` overrides the calibration rates
    (determinism in tests; replaying a recorded capture).
    ``applyable_only`` restricts the space to
    :data:`FAMILY_TRAIN_OVERRIDES` (the ``--auto_shard apply`` search).
    ``tune_report``: a loaded ``tune_report.json`` dict
    (``analysis/overlap.py``) — every candidate row (and the chosen
    plan) gets the tuner's chosen schedule knobs attached as
    ``tune_knobs``, so an applied plan carries its overlap tuning along.
    Knobs never change the ranking: they are schedule-only transforms
    (TD121), and the priced wire bytes are knob-invariant by
    construction.

    Infeasible candidates are refused through
    :func:`tpu_dist.obs.memory.preflight_check(action="refuse")` — the
    typed :class:`InfeasibleMemoryError` path ``--memory_check`` uses —
    and land in ``refused`` with their byte arithmetic; build/compile
    failures land in ``skips``. Both are counted, never silent. The
    result is a pure function of (families, device count, gauges,
    budget): no wall clock, no environment reads beyond jax's device
    list."""
    import jax

    from tpu_dist.analysis import shardlint
    from tpu_dist.obs import costmodel
    from tpu_dist.obs import memory as memory_lib

    if mesh is None and shard_report is None:
        from tpu_dist.comm import mesh as mesh_lib

        mesh = mesh_lib.data_parallel_mesh()
    n_devices = (
        int(shard_report.get("n_devices", jax.device_count()))
        if shard_report is not None else int(mesh.devices.size)
    )
    gauges, gauge_source = pricing_gauges(gauges)
    budget = hbm_budget_bytes
    if budget is None:
        budget = costmodel.chip_hbm_bytes()

    cands = plan_candidates(n_devices, names)
    if applyable_only:
        cands = [c for c in cands if c in FAMILY_TRAIN_OVERRIDES]

    rows: list = []
    refused: dict = {}
    skips: dict = {}
    for name in cands:
        if shard_report is not None:
            entry = (shard_report.get("families") or {}).get(name)
            if entry is None:
                skips[name] = "not in the supplied shard report"
                continue
        else:
            try:
                entry, _ = shardlint.shard_case(name, mesh)
            except Exception as e:
                skips[name] = f"{type(e).__name__}: {e}"
                continue
        row = price_candidate(
            name, entry, n_devices=n_devices, gauges=gauges
        )
        required = row["static_bytes_per_device"]
        if required is None:
            skips[name] = "no static HBM ledger in the family entry"
            continue
        if row["predicted_step_s"] is None:
            skips[name] = "unpriceable: XLA cost analysis reported nothing"
            continue
        try:
            row["feasibility"] = memory_lib.preflight_check(
                required, budget_bytes=budget,
                headroom=memory_headroom, action="refuse",
            )
        except memory_lib.InfeasibleMemoryError as e:
            refused[name] = {
                "error": f"{type(e).__name__}: {e}",
                "required_bytes": required,
                "budget_bytes": budget,
                "headroom": memory_headroom,
            }
            continue
        rows.append(row)

    # deterministic ranking: fastest predicted step first, family name
    # breaks exact ties (the dp variants price identically on tiny
    # proxies) — NEVER dict order or wall clock
    rows.sort(key=lambda r: (r["predicted_step_s"], r["family"]))
    for i, row in enumerate(rows):
        row["rank"] = i + 1
    if tune_report is not None:
        from tpu_dist.analysis import overlap as overlap_lib

        for row in rows:
            row["tune_knobs"] = overlap_lib.chosen_knobs(
                tune_report, row["family"]
            )

    dev = jax.devices()[0]
    plan = {
        "schema": SCHEMA,
        "backend": dev.platform,
        "device_kind": dev.device_kind,
        "n_devices": n_devices,
        "jax_version": jax.__version__,
        "gauges": gauges,
        "gauge_source": gauge_source,
        "budget": {
            "hbm_budget_bytes": budget,
            "memory_headroom": memory_headroom,
        },
        "candidates": rows,
        "chosen": copy.deepcopy(rows[0]) if rows else None,
        "tune_objective": (
            tune_report.get("objective") if tune_report is not None else None
        ),
        "refused": refused,
        "skips": skips,
        "counts": {
            "candidates": len(rows),
            "refused": len(refused),
            "skipped": len(skips),
        },
    }
    return plan


# --------------------------------------------------------------------------
# TD118 — plan-must-verify
# --------------------------------------------------------------------------


def verify_plan(plan: dict, mesh=None) -> tuple[dict, list[Violation]]:
    """TD118: recompile the chosen family fresh through shardlint and
    require the compiled HLO collective inventory to match what the
    planner priced — per-kind op/element/byte counts exactly, total
    wire bytes exactly. Returns ``(probe, violations)``; ``probe``
    records both inventories and the verdict for the report."""
    chosen = plan.get("chosen")
    if not chosen:
        return {"verified": None, "reason": "no chosen plan"}, []
    from tpu_dist.analysis import shardlint

    name = chosen["family"]
    path = f"<plan:{name}>"
    fresh_entry, _ = shardlint.shard_case(name, mesh)
    fresh = priced_inventory_of(fresh_entry)
    fresh_wire = (fresh_entry.get("hlo") or {}).get("bytes")
    priced = chosen.get("priced_inventory") or {}
    out: list[Violation] = []
    for kind in sorted(set(priced) | set(fresh)):
        p, f = priced.get(kind), fresh.get(kind)
        if p == f:
            continue
        out.append(Violation(
            "TD118", path, 0,
            f"chosen plan's priced {kind} inventory {p} != the freshly "
            f"compiled {f} — the plan's cost basis diverged from what "
            "GSPMD actually emits; re-plan before trusting the ranking",
            snippet=f"{kind}:{p}!={f}",
        ))
    if chosen.get("wire_bytes") != fresh_wire:
        out.append(Violation(
            "TD118", path, 0,
            f"chosen plan priced {chosen.get('wire_bytes')} total wire "
            f"bytes but the fresh compile moves {fresh_wire} — the "
            "step-time ranking was computed on stale wire accounting",
            snippet=f"wire:{chosen.get('wire_bytes')}!={fresh_wire}",
        ))
    probe = {
        "family": name,
        "priced": priced,
        "compiled": fresh,
        "priced_wire_bytes": chosen.get("wire_bytes"),
        "compiled_wire_bytes": fresh_wire,
        "verified": not out,
        "violations": [v.to_json() for v in out],
    }
    return probe, out


def inject_miscost(plan: dict) -> dict:
    """The TD118 acceptance probe: a deep copy of ``plan`` whose chosen
    candidate's priced wire bytes and per-kind inventory are
    deterministically perturbed (doubled + 1, so zero-byte entries
    still shift). :func:`verify_plan` over the result MUST flag TD118 —
    a clean verdict means the detector is dead (CLI exit 2)."""
    out = copy.deepcopy(plan)
    chosen = out.get("chosen")
    if not chosen:
        return out
    wb = chosen.get("wire_bytes")
    chosen["wire_bytes"] = (int(wb) * 2 + 1) if wb is not None else 1
    inv = chosen.get("priced_inventory") or {}
    for e in inv.values():
        e["bytes"] = e["bytes"] * 2 + 1
        e["elems"] = e["elems"] + 1
    if not inv:
        inv["all-reduce"] = {"ops": 1, "elems": 1, "bytes": 1}
        chosen["priced_inventory"] = inv
    return out


# --------------------------------------------------------------------------
# plan_report.json — save / load (forward-compat), rendering
# --------------------------------------------------------------------------


_REQUIRED_CHOSEN_KEYS = (
    "family", "predicted_step_s", "wire_bytes", "priced_inventory",
)


def save_plan_report(report: dict, path: str) -> None:
    import os

    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(report, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)


def load_plan_report(path: str) -> dict:
    """Schema-pinned loader with the summarize ``KNOWN_KINDS``
    forward-compat discipline: the tag must parse as
    ``plan_report_v<N>``; a NEWER version is tolerated — candidates
    missing the v1 pricing keys are skipped with a count into
    ``load_notes`` (additive fields are simply ignored) — while a
    foreign tag, an older-than-supported version, or a SAME-version
    entry missing required keys (corruption, not forward compat) raises
    the typed :class:`PlanReportError`."""
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if not isinstance(data, dict):
        raise PlanReportError(f"{path}: not a JSON object")
    tag = data.get("schema")
    m = _SCHEMA_RE.match(tag) if isinstance(tag, str) else None
    if not m:
        raise PlanReportError(
            f"{path}: schema {tag!r} is not a plan_report tag — "
            "regenerate with `make plan-report`"
        )
    ver = int(m.group(1))
    if ver < SCHEMA_VERSION:
        raise PlanReportError(
            f"{path}: schema {tag!r} predates v{SCHEMA_VERSION} — "
            "regenerate with `make plan-report`"
        )
    newer = ver > SCHEMA_VERSION
    skipped: dict = {}
    cands = data.get("candidates")
    if not isinstance(cands, list):
        raise PlanReportError(f"{path}: no 'candidates' list")
    kept = []
    for entry in cands:
        missing = [k for k in _REQUIRED_CHOSEN_KEYS if k not in entry]
        if not missing:
            kept.append(entry)
            continue
        if not newer:
            raise PlanReportError(
                f"{path}: candidate {entry.get('family')!r} is missing "
                f"{missing}"
            )
        skipped[str(entry.get("family"))] = missing
    data["candidates"] = kept
    chosen = data.get("chosen")
    if chosen is not None:
        missing = [k for k in _REQUIRED_CHOSEN_KEYS if k not in chosen]
        if missing and not newer:
            raise PlanReportError(
                f"{path}: chosen plan is missing {missing}"
            )
        if missing:
            skipped["<chosen>"] = missing
            data["chosen"] = None
    if newer:
        data["load_notes"] = {
            "newer_schema": tag,
            "reader_version": SCHEMA_VERSION,
            "skipped_candidates": skipped,
            "skipped_count": len(skipped),
        }
    return data


def format_text(plan: dict) -> str:
    """Terminal rendering: the ranked table, refusals, skips, verdicts."""
    from tpu_dist.obs.memory import fmt_bytes

    c = plan["counts"]
    lines = [
        f"autoplan: {c['candidates']} candidate(s) over "
        f"{plan['n_devices']} device(s)"
        + (f", {c['refused']} REFUSED (HBM)" if c["refused"] else "")
        + (f", {c['skipped']} skipped" if c["skipped"] else "")
        + f"  [rates: {plan.get('gauge_source')}]"
    ]
    for row in plan.get("candidates", []):
        pred = row.get("predicted_step_s")
        req = row.get("static_bytes_per_device")
        lines.append(
            f"  #{row['rank']:<2} {row['family']:<16} "
            f"pred_step {pred * 1e3:>9.4g} ms  "
            f"wire {row.get('wire_bytes') or 0:>8} B  "
            f"hbm {fmt_bytes(req):>10}/dev"
            + ("" if row.get("applyable") else "  [plan-only]")
        )
    for name, why in sorted(plan.get("refused", {}).items()):
        lines.append(
            f"  --  {name:<16} REFUSED: needs "
            f"{fmt_bytes(why.get('required_bytes') or 0)}/dev over the "
            f"budget ({why.get('error')})"
        )
    for name, why in sorted(plan.get("skips", {}).items()):
        lines.append(f"  --  {name:<16} SKIPPED: {why}")
    chosen = plan.get("chosen")
    if chosen:
        lines.append(
            f"autoplan: chosen {chosen['family']} "
            f"(pred_step {chosen['predicted_step_s'] * 1e3:.4g} ms)"
        )
    else:
        lines.append("autoplan: NO feasible candidate")
    probe = plan.get("verification")
    if probe is not None:
        lines.append(
            "autoplan: TD118 "
            + ("verified — compiled inventory matches the priced one"
               if probe.get("verified")
               else f"FAILED — {len(probe.get('violations', []))} "
                    "inventory mismatch(es)")
        )
    inj = plan.get("injected_miscost_probe")
    if inj is not None:
        # the probe outcome must be visible, not exit-code-only: a CI log
        # reader should see the detector proven live without rerunning
        lines.append(
            "autoplan: inject-miscost probe "
            + (f"CAUGHT ({len(inj.get('violations', []))} violation(s)) "
               "— the TD118 detector is live"
               if inj.get("caught")
               else "came back CLEAN — the TD118 detector is dead")
        )
    return "\n".join(lines)
