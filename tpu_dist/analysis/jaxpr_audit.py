"""Layer 2 — jaxpr audit of the compiled train steps (rules TD101-TD103).

Where Layer 1 reads *source*, this layer reads the *program*: each
registered audit case builds a real step function on an emulated CPU mesh,
traces it abstractly (``jax.make_jaxpr`` — no device cycles, no
compilation), and walks the closed jaxpr:

* **TD101** — collective ops (``psum``/``all_gather``/``psum_scatter``/
  ``ppermute``/``all_to_all``) are counted and asserted against the
  parallelism config's budget. The budget encodes real invariants: grad
  accumulation must NOT add collectives (torch's ``no_sync`` contract —
  the single post-scan pmean), and ZeRO-1 must replace the grad allreduce
  with exactly one reduce-scatter + one all-gather (arXiv:2004.13336).
* **TD102** — ``device_put`` transfer ops inside the step are host↔device
  traffic on the hot path; the budget is zero.
* **TD103** — bf16→f32 ``convert_element_type`` ops in the mixed-precision
  case are counted against the number the bf16 policy declares (params
  cast transpose + the f32 metric readouts). One more means some op is
  silently promoting — f32 math and double the bytes where bf16 was asked
  for (the promotion-creep failure mode of arXiv:2011.03641 §4).

Counts are per-*equation*: ``lax.pmean`` over a whole grad pytree emits ONE
multi-operand ``psum`` eqn, so budgets stay stable as models grow leaves.

Register additional cases with :func:`register_audit_case` (builders get
the mesh, return ``(fn, example_args, CollectiveBudget)``).
"""

from __future__ import annotations

import dataclasses
from collections import Counter
from typing import Callable, Optional

from tpu_dist.analysis.rules import Violation

COLLECTIVE_PRIMS = {
    "psum",
    "pmin",
    "pmax",
    "all_gather",
    "all_to_all",
    "ppermute",
    "pgather",
    "psum_scatter",
    "reduce_scatter",
}
TRANSFER_PRIMS = {"device_put"}


@dataclasses.dataclass
class CollectiveBudget:
    """Expected jaxpr-op counts for one step under one parallelism config.

    ``collectives`` maps primitive name → exact expected eqn count (prims
    absent from the map must not appear at all). ``transfers`` is the
    allowed ``device_put`` count (0 on any sane hot path). ``bf16_to_f32``
    is the declared number of bf16→f32 converts, or None to skip TD103
    (pure-f32 cases)."""

    collectives: dict[str, int]
    transfers: int = 0
    bf16_to_f32: Optional[int] = None


@dataclasses.dataclass
class AuditCase:
    name: str
    # builder(mesh) -> (step_fn, example_args_tuple, CollectiveBudget)
    builder: Callable


_CASES: dict[str, AuditCase] = {}


def register_audit_case(name: str, builder: Callable) -> None:
    _CASES[name] = AuditCase(name, builder)


def registered_cases() -> list[str]:
    return sorted(_CASES)


# --------------------------------------------------------------------------
# jaxpr walking
# --------------------------------------------------------------------------


def _jaxpr_classes():
    """(ClosedJaxpr, Jaxpr) wherever this jax keeps them — ``jax.core`` up
    to 0.5.x, ``jax.extend.core`` afterwards."""
    try:
        from jax.extend.core import ClosedJaxpr, Jaxpr  # type: ignore
    except ImportError:
        from jax.core import ClosedJaxpr, Jaxpr  # type: ignore
    return ClosedJaxpr, Jaxpr


def _sub_jaxprs(params: dict):
    ClosedJaxpr, Jaxpr = _jaxpr_classes()
    for v in params.values():
        vals = v if isinstance(v, (list, tuple)) else [v]
        for item in vals:
            if isinstance(item, ClosedJaxpr):
                yield item.jaxpr
            elif isinstance(item, Jaxpr):
                yield item


def _walk_eqns(jaxpr, mult: int = 1):
    """Yield ``(eqn, multiplicity)`` — ops inside a ``scan`` body run once
    per trip, so their counts are multiplied by the trip count. Without
    this, a grad pmean accidentally moved INSIDE the accumulation scan
    (the exact no_sync violation TD101 exists to catch) would count the
    same as the single post-scan reduce."""
    for eqn in jaxpr.eqns:
        yield eqn, mult
        sub_mult = mult
        if eqn.primitive.name == "scan":
            sub_mult = mult * int(eqn.params.get("length", 1))
        for sub in _sub_jaxprs(eqn.params):
            yield from _walk_eqns(sub, sub_mult)


def trace_counts(fn, *args) -> dict:
    """Abstractly trace ``fn(*args)`` and tally the audited op classes."""
    import jax
    import jax.numpy as jnp

    closed = jax.make_jaxpr(fn)(*args)
    collectives: Counter = Counter()
    transfers = 0
    bf16_to_f32 = 0
    for eqn, mult in _walk_eqns(closed.jaxpr):
        name = eqn.primitive.name
        if name in COLLECTIVE_PRIMS:
            collectives[name] += mult
        elif name in TRANSFER_PRIMS:
            transfers += mult
        elif name == "convert_element_type":
            (invar,) = eqn.invars
            src = getattr(getattr(invar, "aval", None), "dtype", None)
            dst = eqn.params.get("new_dtype")
            if src == jnp.bfloat16 and dst == jnp.float32:
                bf16_to_f32 += mult
    return {
        "collectives": dict(sorted(collectives.items())),
        "transfers": transfers,
        "bf16_to_f32": bf16_to_f32,
    }


# --------------------------------------------------------------------------
# The default registered cases: the data-parallel train-step family.
# --------------------------------------------------------------------------


class _AuditMLP:
    """BN-free two-layer MLP: the smallest model with a multi-leaf param
    tree (4 leaves) that still exercises the full step machinery."""

    in_dim, width, classes = 12, 16, 10

    def init(self, key):
        import jax
        import jax.numpy as jnp

        k1, k2 = jax.random.split(key)
        params = {
            "w1": jax.random.normal(k1, (self.in_dim, self.width), jnp.float32) * 0.1,
            "b1": jnp.zeros((self.width,), jnp.float32),
            "w2": jax.random.normal(k2, (self.width, self.classes), jnp.float32) * 0.1,
            "b2": jnp.zeros((self.classes,), jnp.float32),
        }
        return params, {}

    def apply(self, params, state, x, *, train=False, axis_name=None, **kw):
        import jax.numpy as jnp

        x = x.reshape(x.shape[0], -1)
        h = jnp.maximum(x @ params["w1"] + params["b1"], 0.0)
        return h @ params["w2"] + params["b2"], state


def _dp_setup(mesh, **step_kwargs):
    import jax
    import jax.numpy as jnp

    from tpu_dist.train.optim import SGD
    from tpu_dist.train.state import TrainState
    from tpu_dist.train.step import init_sharded_opt_state, make_train_step

    model = _AuditMLP()
    params, bn = model.init(jax.random.PRNGKey(0))
    opt = SGD(momentum=0.9, weight_decay=1e-4)
    if step_kwargs.get("shard_weight_update"):
        opt_state = init_sharded_opt_state(params, mesh)
    else:
        opt_state = opt.init(params)
    state = TrainState(params, bn, opt_state, jnp.zeros((), jnp.int32))
    step = make_train_step(model.apply, opt, mesh, sync_bn=False, **step_kwargs)
    n = mesh.devices.size
    batch = 8 * n  # 8 per device: divisible by the accum case's K=4
    images = jax.ShapeDtypeStruct((batch, 2, 2, 3), jnp.float32)
    labels = jax.ShapeDtypeStruct((batch,), jnp.int32)
    lr = jax.ShapeDtypeStruct((), jnp.float32)
    return step, (state, images, labels, lr)


# The plain data-parallel step's collective inventory (per compiled step):
#   psum x4: grad-tree pmean (1 multi-operand eqn), metric loss pmean,
#            acc1 correct-count psum, acc5 correct-count psum
#            (the `psum(1, axis)` device-count terms fold to constants
#            at trace time — no eqn).
_DP_BUDGET = {"psum": 4}
# ZeRO-1 swaps the grad psum for reduce-scatter + param all-gather
# (arXiv:2004.13336): 3 metric psums remain. (lax.psum_scatter lowers to
# the `reduce_scatter` primitive.)
_ZERO1_BUDGET = {"psum": 3, "reduce_scatter": 1, "all_gather": 1}
# bf16 compute declares: 4 bf16→f32 converts from the params-cast transpose
# (one per param leaf, rebuilding f32 grads) + 1 logits→f32 for metrics
# + 1 loss→f32 for the metric pmean.
_BF16_CONVERTS = 6


def _case_dp_sgd(mesh):
    fn, args = _dp_setup(mesh)
    return fn, args, CollectiveBudget(dict(_DP_BUDGET), bf16_to_f32=None)


def _case_dp_sgd_accum(mesh):
    # torch no_sync contract: K local sub-steps, ONE cross-replica reduce —
    # the budget is IDENTICAL to the K=1 step.
    fn, args = _dp_setup(mesh, grad_accum_steps=4)
    return fn, args, CollectiveBudget(dict(_DP_BUDGET), bf16_to_f32=None)


def _case_dp_bf16(mesh):
    import jax.numpy as jnp

    fn, args = _dp_setup(mesh, compute_dtype=jnp.bfloat16)
    return fn, args, CollectiveBudget(dict(_DP_BUDGET), bf16_to_f32=_BF16_CONVERTS)


def _case_zero1_sgd(mesh):
    fn, args = _dp_setup(mesh, shard_weight_update=True)
    return fn, args, CollectiveBudget(dict(_ZERO1_BUDGET), bf16_to_f32=None)


register_audit_case("dp_sgd", _case_dp_sgd)
register_audit_case("dp_sgd_accum4", _case_dp_sgd_accum)
register_audit_case("dp_bf16", _case_dp_bf16)
register_audit_case("zero1_sgd", _case_zero1_sgd)


# --------------------------------------------------------------------------
# Driving + budget comparison
# --------------------------------------------------------------------------


def audit_case(name: str, mesh=None) -> tuple[dict, list[Violation]]:
    from tpu_dist.comm import mesh as mesh_lib

    if name not in _CASES:
        raise ValueError(
            f"unknown audit case {name!r}; registered: {registered_cases()}"
        )
    case = _CASES[name]
    m = mesh if mesh is not None else mesh_lib.data_parallel_mesh()
    fn, args, budget = case.builder(m)
    counts = trace_counts(fn, *args)
    return counts, _compare(name, counts, budget)


def audit_all(mesh=None, names=None) -> tuple[dict, list[Violation]]:
    """Run every (or the named) registered case. Returns
    ``(report, violations)`` where report maps case → op counts."""
    report: dict = {}
    violations: list[Violation] = []
    for name in names if names is not None else registered_cases():
        counts, vs = audit_case(name, mesh)
        report[name] = counts
        violations.extend(vs)
    return report, violations


def _compare(name: str, counts: dict, budget: CollectiveBudget) -> list[Violation]:
    out: list[Violation] = []
    path = f"<jaxpr:{name}>"
    actual = counts["collectives"]
    for prim in sorted(set(actual) | set(budget.collectives)):
        want, got = budget.collectives.get(prim, 0), actual.get(prim, 0)
        if want != got:
            out.append(
                Violation(
                    "TD101",
                    path,
                    0,
                    f"{prim}: expected {want} per step, jaxpr has {got} — "
                    "the compiled step's collective inventory drifted from "
                    "the parallelism config's budget",
                    snippet=f"{prim}:{got}",
                )
            )
    if counts["transfers"] > budget.transfers:
        out.append(
            Violation(
                "TD102",
                path,
                0,
                f"{counts['transfers']} device_put transfer op(s) inside "
                f"the compiled step (budget {budget.transfers}) — "
                "host↔device traffic on the hot path",
                snippet=f"device_put:{counts['transfers']}",
            )
        )
    if budget.bf16_to_f32 is not None and counts["bf16_to_f32"] != budget.bf16_to_f32:
        out.append(
            Violation(
                "TD103",
                path,
                0,
                f"{counts['bf16_to_f32']} bf16→f32 converts, mixed-precision "
                f"policy declares {budget.bf16_to_f32} — an op is implicitly "
                "promoting to f32",
                snippet=f"bf16_to_f32:{counts['bf16_to_f32']}",
            )
        )
    return out
