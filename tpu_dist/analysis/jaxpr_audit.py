"""Layer 2 — jaxpr audit of the compiled train steps (rules TD101-TD103).

Where Layer 1 reads *source*, this layer reads the *program*: each
registered audit case builds a real step function on an emulated CPU mesh,
traces it abstractly (``jax.make_jaxpr`` — no device cycles, no
compilation), and walks the closed jaxpr:

* **TD101** — collective ops (``psum``/``all_gather``/``psum_scatter``/
  ``ppermute``/``all_to_all``) are counted and asserted against the
  parallelism config's budget. The budget encodes real invariants: grad
  accumulation must NOT add collectives (torch's ``no_sync`` contract —
  the single post-scan pmean), and ZeRO-1 must replace the grad allreduce
  with exactly one reduce-scatter + one all-gather (arXiv:2004.13336).
* **TD102** — ``device_put`` transfer ops inside the step are host↔device
  traffic on the hot path; the budget is zero.
* **TD103** — bf16→f32 ``convert_element_type`` ops in the mixed-precision
  case are counted against the number the bf16 policy declares (params
  cast transpose + the f32 metric readouts). One more means some op is
  silently promoting — f32 math and double the bytes where bf16 was asked
  for (the promotion-creep failure mode of arXiv:2011.03641 §4).
* **TD104** — static wire-byte accounting of the gradient collectives
  under the compressed wire formats (``grad_compression``): each
  collective eqn is costed with a ring model (``psum`` = reduce-scatter +
  all-gather = 2 payload legs; ``all_to_all``/``reduce_scatter`` = its
  operand once; ``all_gather`` = its output once) and bucketed into
  *payload* (the gradient/param data — int8 under the quantized modes)
  vs *sideband* (quantization scales, scalar metric reduces). The int8
  modes must keep gradient payload ≤0.5× the bf16 mode's and ≤0.25× the
  uncompressed mode's — verified per step for the streaming path and per
  epoch for the fused-``lax.scan`` path. Sideband is reported (never
  hidden) but not gated: the f32 scales are a factor ``chunk`` (256)
  smaller than the payload in ELEMENTS — ``chunk/4`` (64×, ~1.6%) in
  bytes — by construction, independent of the wire format choice.

Counts are per-*equation*: ``lax.pmean`` over a whole grad pytree emits ONE
multi-operand ``psum`` eqn, so budgets stay stable as models grow leaves.

Register additional cases with :func:`register_audit_case` (builders get
the mesh, return ``(fn, example_args, CollectiveBudget)``).
"""

from __future__ import annotations

import dataclasses
from collections import Counter
from typing import Callable, Optional

from tpu_dist.analysis.rules import Violation

COLLECTIVE_PRIMS = {
    "psum",
    "pmin",
    "pmax",
    "all_gather",
    "all_to_all",
    "ppermute",
    "pgather",
    "psum_scatter",
    "reduce_scatter",
}
TRANSFER_PRIMS = {"device_put"}


@dataclasses.dataclass
class CollectiveBudget:
    """Expected jaxpr-op counts for one step under one parallelism config.

    ``collectives`` maps primitive name → exact expected eqn count (prims
    absent from the map must not appear at all). ``transfers`` is the
    allowed ``device_put`` count (0 on any sane hot path). ``bf16_to_f32``
    is the declared number of bf16→f32 converts, or None to skip TD103
    (pure-f32 cases)."""

    collectives: dict[str, int]
    transfers: int = 0
    bf16_to_f32: Optional[int] = None


@dataclasses.dataclass
class AuditCase:
    name: str
    # builder(mesh) -> (step_fn, example_args_tuple, CollectiveBudget)
    builder: Callable


_CASES: dict[str, AuditCase] = {}


def register_audit_case(name: str, builder: Callable) -> None:
    _CASES[name] = AuditCase(name, builder)


def registered_cases() -> list[str]:
    return sorted(_CASES)


# --------------------------------------------------------------------------
# jaxpr walking
# --------------------------------------------------------------------------


def _jaxpr_classes():
    """(ClosedJaxpr, Jaxpr) wherever this jax keeps them — ``jax.core`` up
    to 0.5.x, ``jax.extend.core`` afterwards."""
    try:
        from jax.extend.core import ClosedJaxpr, Jaxpr  # type: ignore
    except ImportError:
        from jax.core import ClosedJaxpr, Jaxpr  # type: ignore
    return ClosedJaxpr, Jaxpr


def _sub_jaxprs(params: dict):
    ClosedJaxpr, Jaxpr = _jaxpr_classes()
    for v in params.values():
        vals = v if isinstance(v, (list, tuple)) else [v]
        for item in vals:
            if isinstance(item, ClosedJaxpr):
                yield item.jaxpr
            elif isinstance(item, Jaxpr):
                yield item


def _walk_eqns(jaxpr, mult: int = 1):
    """Yield ``(eqn, multiplicity)`` — ops inside a ``scan`` body run once
    per trip, so their counts are multiplied by the trip count. Without
    this, a grad pmean accidentally moved INSIDE the accumulation scan
    (the exact no_sync violation TD101 exists to catch) would count the
    same as the single post-scan reduce."""
    for eqn in jaxpr.eqns:
        yield eqn, mult
        sub_mult = mult
        if eqn.primitive.name == "scan":
            sub_mult = mult * int(eqn.params.get("length", 1))
        for sub in _sub_jaxprs(eqn.params):
            yield from _walk_eqns(sub, sub_mult)


# Per-replica wire legs of each collective under the standard ring model:
# psum (allreduce) = reduce-scatter + all-gather of its operand; the
# scatter/gather/transpose prims each move their payload once. The common
# (n-1)/n send fraction cancels in every ratio TD104 checks, so it is left
# out — these are RELATIVE budgets, not absolute bandwidth estimates.
_WIRE_LEGS = {
    "psum": 2,
    "pmin": 2,
    "pmax": 2,
    "reduce_scatter": 1,
    "psum_scatter": 1,
    "all_to_all": 1,
    "ppermute": 1,
    "all_gather": 1,  # costed on its OUTPUT (operand is the local shard)
    "pgather": 1,
}
# Float collectives at/above this element count are gradient/param payload;
# below it they are sideband (scalar metric reduces). Only used when the
# step has no int8 payload to calibrate against.
_PAYLOAD_MIN_ELEMS = 32


def _eqn_wire(eqn) -> tuple[int, int, bool]:
    """``(elements, bytes_on_wire, is_int)`` for one collective eqn."""
    import numpy as np

    def total(vars_):
        elems = byts = 0
        for v in vars_:
            aval = getattr(v, "aval", None)
            shape = getattr(aval, "shape", ())
            dt = getattr(aval, "dtype", None)
            n = int(np.prod(shape)) if shape else 1
            elems += n
            byts += n * (np.dtype(dt).itemsize if dt is not None else 4)
        return elems, byts

    in_e, in_b = total(eqn.invars)
    out_e, out_b = total(eqn.outvars)
    legs = _WIRE_LEGS.get(eqn.primitive.name, 1)
    # all_gather/pgather: the wire carries the gathered OUTPUT; everything
    # else is costed on what the replica feeds in
    e, b = (out_e, out_b) if eqn.primitive.name in ("all_gather", "pgather") else (in_e, in_b)
    dt = getattr(getattr(eqn.invars[0], "aval", None), "dtype", None)
    # quantized payload is specifically the 8-bit wire (int32 scalar
    # METRIC reduces — correct-count psums — are sideband, not payload)
    is_quant = dt is not None and np.dtype(dt).itemsize == 1
    return max(in_e, out_e), legs * b, is_quant


def _wire_buckets(records) -> dict:
    """Bucket ``(prim, elems, bytes, is_quant, mult)`` collective records
    into payload vs sideband. int8 collectives are always quantized
    payload; other collectives are payload when within a factor 8 of the
    LARGEST message in the step (the gradient/param data, whatever its
    dtype — so the cut is identical across wire modes and a mid-size
    non-gradient reduce, e.g. SyncBN statistics, lands in the same bucket
    under every mode), sideband below it (quantization scales — chunking
    keeps them ≤ payload/16 in elements — and scalar metric reduces)."""
    max_e = max((e for _, e, _, _, _ in records), default=0)
    cut = max(max_e / 8.0, float(_PAYLOAD_MIN_ELEMS))
    payload = quant = side = 0
    by_prim: Counter = Counter()
    for prim, elems, byts, is_q, mult in records:
        by_prim[prim] += byts * mult
        if is_q:
            payload += byts * mult
            quant += byts * mult
        elif elems >= cut:
            payload += byts * mult
        else:
            side += byts * mult
    return {
        "payload_bytes": payload,
        "quantized_payload_bytes": quant,
        "sideband_bytes": side,
        "by_prim": dict(sorted(by_prim.items())),
    }


def trace_counts(fn, *args) -> dict:
    """Abstractly trace ``fn(*args)`` and tally the audited op classes."""
    import jax
    import jax.numpy as jnp

    closed = jax.make_jaxpr(fn)(*args)
    collectives: Counter = Counter()
    transfers = 0
    bf16_to_f32 = 0
    wire_records = []
    for eqn, mult in _walk_eqns(closed.jaxpr):
        name = eqn.primitive.name
        if name in COLLECTIVE_PRIMS:
            collectives[name] += mult
            elems, byts, is_int = _eqn_wire(eqn)
            wire_records.append((name, elems, byts, is_int, mult))
        elif name in TRANSFER_PRIMS:
            transfers += mult
        elif name == "convert_element_type":
            (invar,) = eqn.invars
            src = getattr(getattr(invar, "aval", None), "dtype", None)
            dst = eqn.params.get("new_dtype")
            if src == jnp.bfloat16 and dst == jnp.float32:
                bf16_to_f32 += mult
    return {
        "collectives": dict(sorted(collectives.items())),
        "transfers": transfers,
        "bf16_to_f32": bf16_to_f32,
        "wire": _wire_buckets(wire_records),
    }


# --------------------------------------------------------------------------
# The default registered cases: the data-parallel train-step family.
# --------------------------------------------------------------------------


class _AuditMLP:
    """BN-free two-layer MLP: the smallest model with a multi-leaf param
    tree (4 leaves) that still exercises the full step machinery.

    ``classes=16`` keeps the TOTAL param count (480) divisible by every
    emulated mesh width (1/2/4/8), so the quantized wire formats' flat
    padding is zero and the TD104 byte ratios are exact (0.5×/0.25×), not
    0.5×+padding. No budget depends on the head width."""

    in_dim, width, classes = 12, 16, 16

    def init(self, key):
        import jax
        import jax.numpy as jnp

        k1, k2 = jax.random.split(key)
        params = {
            "w1": jax.random.normal(k1, (self.in_dim, self.width), jnp.float32) * 0.1,
            "b1": jnp.zeros((self.width,), jnp.float32),
            "w2": jax.random.normal(k2, (self.width, self.classes), jnp.float32) * 0.1,
            "b2": jnp.zeros((self.classes,), jnp.float32),
        }
        return params, {}

    def apply(self, params, state, x, *, train=False, axis_name=None, **kw):
        import jax.numpy as jnp

        x = x.reshape(x.shape[0], -1)
        h = jnp.maximum(x @ params["w1"] + params["b1"], 0.0)
        return h @ params["w2"] + params["b2"], state


def _dp_setup(mesh, **step_kwargs):
    import jax
    import jax.numpy as jnp

    from tpu_dist.train.optim import SGD
    from tpu_dist.train.state import TrainState
    from tpu_dist.train.step import (
        init_ef_state,
        init_sharded_opt_state,
        make_train_step,
    )

    model = _AuditMLP()
    params, bn = model.init(jax.random.PRNGKey(0))
    opt = SGD(momentum=0.9, weight_decay=1e-4)
    zero1 = bool(step_kwargs.get("shard_weight_update"))
    if zero1:
        opt_state = init_sharded_opt_state(params, mesh)
    else:
        opt_state = opt.init(params)
    state = TrainState(params, bn, opt_state, jnp.zeros((), jnp.int32))
    if step_kwargs.get("grad_compression") == "int8_ef":
        state = state._replace(ef=init_ef_state(params, mesh, zero1=zero1))
    step = make_train_step(model.apply, opt, mesh, sync_bn=False, **step_kwargs)
    n = mesh.devices.size
    batch = 8 * n  # 8 per device: divisible by the accum case's K=4
    images = jax.ShapeDtypeStruct((batch, 2, 2, 3), jnp.float32)
    labels = jax.ShapeDtypeStruct((batch,), jnp.int32)
    lr = jax.ShapeDtypeStruct((), jnp.float32)
    return step, (state, images, labels, lr)


def _fused_setup(mesh, mode: str):
    """The fused-epoch (``train/epoch.py``) twin of :func:`_dp_setup`:
    device-resident dataset sized for 2 scan steps per epoch, so the
    per-trip collective multiplication is exercised."""
    import jax
    import jax.numpy as jnp

    from tpu_dist.train.epoch import make_fused_epoch
    from tpu_dist.train.optim import SGD
    from tpu_dist.train.state import TrainState
    from tpu_dist.train.step import init_ef_state

    model = _AuditMLP()
    params, bn = model.init(jax.random.PRNGKey(0))
    opt = SGD(momentum=0.9, weight_decay=1e-4)
    state = TrainState(params, bn, opt.init(params), jnp.zeros((), jnp.int32))
    if mode == "int8_ef":
        state = state._replace(ef=init_ef_state(params, mesh))
    epoch = make_fused_epoch(
        model.apply, opt, mesh, batch_per_device=4, sync_bn=False,
        compute_dtype=jnp.float32, grad_compression=mode,
    )
    n = mesh.devices.size
    images = jax.ShapeDtypeStruct((8 * n, 2, 2, 3), jnp.uint8)  # 2 steps
    labels = jax.ShapeDtypeStruct((8 * n,), jnp.int32)
    lr = jax.ShapeDtypeStruct((), jnp.float32)
    epoch_idx = jax.ShapeDtypeStruct((), jnp.int32)
    return epoch, (state, images, labels, lr, epoch_idx)


# The plain data-parallel step's collective inventory (per compiled step):
#   psum x4: grad-tree pmean (1 multi-operand eqn), metric loss pmean,
#            acc1 correct-count psum, acc5 correct-count psum
#            (the `psum(1, axis)` device-count terms fold to constants
#            at trace time — no eqn).
_DP_BUDGET = {"psum": 4}
# ZeRO-1 swaps the grad psum for reduce-scatter + param all-gather
# (arXiv:2004.13336): 3 metric psums remain. (lax.psum_scatter lowers to
# the `reduce_scatter` primitive.)
_ZERO1_BUDGET = {"psum": 3, "reduce_scatter": 1, "all_gather": 1}
# The quantized two-stage reduce (EQuARX-style RS+AG, step.py): the grad
# psum becomes int8 all_to_all (payload + scale sideband) followed by int8
# all_gather (payload + sideband) — 4 collective eqns replacing 1, moving
# a quarter of the f32 bytes. 3 metric psums remain. Error feedback is
# pure local arithmetic: int8_ef's budget is IDENTICAL to int8's.
_DP_INT8_BUDGET = {"psum": 3, "all_to_all": 2, "all_gather": 2}
# ZeRO-1 quantized: the reduce-scatter leg is the int8 all_to_all pair;
# the param all-gather stays in the param dtype (weights, not gradients).
_ZERO1_INT8_BUDGET = {"psum": 3, "all_to_all": 2, "all_gather": 1}
# Fused-epoch budgets: per-trip collectives × the 2 scan steps.
_FUSED_STEPS = 2
# bf16 compute declares: 4 bf16→f32 converts from the params-cast transpose
# (one per param leaf, rebuilding f32 grads) + 1 logits→f32 for metrics
# + 1 loss→f32 for the metric pmean.
_BF16_CONVERTS = 6


# The dp/zero1 flag combos come from the ONE config-family registry
# (``train/step.py::SHARD_CONFIG_FAMILIES``) shared with the shardlint
# HLO audit and the future --auto_shard planner — a family added there is
# automatically the same flags here, so the two static accountings (jaxpr
# ring model, compiled HLO) always describe the same program.


def _family_setup(mesh, family: str):
    from tpu_dist.train.step import family_step_kwargs

    return _dp_setup(mesh, **family_step_kwargs(family))


def _case_dp_sgd(mesh):
    fn, args = _family_setup(mesh, "dp_sgd")
    return fn, args, CollectiveBudget(dict(_DP_BUDGET), bf16_to_f32=None)


def _case_dp_sgd_accum(mesh):
    # torch no_sync contract: K local sub-steps, ONE cross-replica reduce —
    # the budget is IDENTICAL to the K=1 step.
    fn, args = _family_setup(mesh, "dp_sgd_accum4")
    return fn, args, CollectiveBudget(dict(_DP_BUDGET), bf16_to_f32=None)


def _case_dp_bf16(mesh):
    fn, args = _family_setup(mesh, "dp_bf16")
    return fn, args, CollectiveBudget(dict(_DP_BUDGET), bf16_to_f32=_BF16_CONVERTS)


def _case_zero1_sgd(mesh):
    fn, args = _family_setup(mesh, "zero1_sgd")
    return fn, args, CollectiveBudget(dict(_ZERO1_BUDGET), bf16_to_f32=None)


def _case_dp_wire_bf16(mesh):
    # the bf16 WIRE format (grad_compression='bf16'; compute stays f32) —
    # the 2-bytes/element reference point of the TD104 wire ratios. NOT
    # dp_bf16, which is the bf16 COMPUTE policy over an f32 wire.
    fn, args = _family_setup(mesh, "dp_wire_bf16")
    return fn, args, CollectiveBudget(dict(_DP_BUDGET), bf16_to_f32=None)


def _case_dp_int8(mesh):
    fn, args = _family_setup(mesh, "dp_int8")
    return fn, args, CollectiveBudget(dict(_DP_INT8_BUDGET), bf16_to_f32=None)


def _case_dp_int8_ef(mesh):
    fn, args = _family_setup(mesh, "dp_int8_ef")
    return fn, args, CollectiveBudget(dict(_DP_INT8_BUDGET), bf16_to_f32=None)


def _case_zero1_int8(mesh):
    fn, args = _family_setup(mesh, "zero1_int8")
    return fn, args, CollectiveBudget(dict(_ZERO1_INT8_BUDGET), bf16_to_f32=None)


def _case_dp_device_metrics(mesh):
    # --device_metrics: the health scalars (grad/param norm, update ratio,
    # nonfinite count) are computed on the POST-pmean gradients — the
    # collective budget is IDENTICAL to the plain step's (TD107's
    # flag-on half, enforced here through the ordinary TD101 machinery)
    fn, args = _family_setup(mesh, "dp_device_metrics")
    return fn, args, CollectiveBudget(dict(_DP_BUDGET), bf16_to_f32=None)


def _fused_budget(per_step: dict) -> dict:
    return {k: v * _FUSED_STEPS for k, v in per_step.items()}


def _case_fused(mode: str, budget: dict):
    def build(mesh):
        fn, args = _fused_setup(mesh, mode)
        return fn, args, CollectiveBudget(_fused_budget(budget), bf16_to_f32=None)

    return build


register_audit_case("dp_sgd", _case_dp_sgd)
register_audit_case("dp_sgd_accum4", _case_dp_sgd_accum)
register_audit_case("dp_bf16", _case_dp_bf16)
register_audit_case("zero1_sgd", _case_zero1_sgd)
register_audit_case("dp_wire_bf16", _case_dp_wire_bf16)
register_audit_case("dp_int8", _case_dp_int8)
register_audit_case("dp_int8_ef", _case_dp_int8_ef)
register_audit_case("zero1_int8", _case_zero1_int8)
register_audit_case("dp_device_metrics", _case_dp_device_metrics)
register_audit_case("fused_none", _case_fused("none", _DP_BUDGET))
register_audit_case("fused_bf16", _case_fused("bf16", _DP_BUDGET))
register_audit_case("fused_int8", _case_fused("int8", _DP_INT8_BUDGET))
register_audit_case("fused_int8_ef", _case_fused("int8_ef", _DP_INT8_BUDGET))


# --------------------------------------------------------------------------
# Driving + budget comparison
# --------------------------------------------------------------------------


def audit_case(name: str, mesh=None) -> tuple[dict, list[Violation]]:
    from tpu_dist.comm import mesh as mesh_lib

    if name not in _CASES:
        raise ValueError(
            f"unknown audit case {name!r}; registered: {registered_cases()}"
        )
    case = _CASES[name]
    m = mesh if mesh is not None else mesh_lib.data_parallel_mesh()
    fn, args, budget = case.builder(m)
    counts = trace_counts(fn, *args)
    return counts, _compare(name, counts, budget)


# TD104: (quantized case, reference case, max payload-byte ratio). Every
# pair present in a report is checked; equality is allowed (the int8 modes
# land EXACTLY on 0.5×bf16 / 0.25×f32 when the flat padding is zero).
_WIRE_RATIO_CHECKS = (
    ("dp_int8", "dp_wire_bf16", 0.5),
    ("dp_int8", "dp_sgd", 0.25),
    ("dp_int8_ef", "dp_wire_bf16", 0.5),
    ("dp_int8_ef", "dp_sgd", 0.25),
    ("fused_int8", "fused_bf16", 0.5),
    ("fused_int8", "fused_none", 0.25),
    ("fused_int8_ef", "fused_bf16", 0.5),
    ("fused_int8_ef", "fused_none", 0.25),
)


def wire_ratio_violations(report: dict) -> list[Violation]:
    """TD104 over a case→counts report: quantized gradient payload must
    honor the declared fraction of its reference mode's payload."""
    out: list[Violation] = []
    for qcase, ref, lim in _WIRE_RATIO_CHECKS:
        if qcase not in report or ref not in report:
            continue
        qb = report[qcase]["wire"]["payload_bytes"]
        rb = report[ref]["wire"]["payload_bytes"]
        if rb and qb > lim * rb:
            out.append(
                Violation(
                    "TD104",
                    f"<jaxpr:{qcase}>",
                    0,
                    f"gradient-collective payload is {qb} B/step vs "
                    f"{ref}'s {rb} B — exceeds the declared {lim}× wire "
                    "budget of the quantized format (a leg decompressed, "
                    "or padding/scale data leaked into the payload)",
                    snippet=f"payload:{qb}>{lim}x{rb}",
                )
            )
    return out


def fault_noop_violations(mesh=None) -> list[Violation]:
    """TD105: the resilience subsystem's zero-cost contract, checked at the
    program level — trace the data-parallel step with fault injection OFF
    and again with a fully-armed composite ``--fault_plan``, and require
    the two jaxprs to be byte-identical. Every injection point is host-side
    (checkpoint writer, loader producer, trainer step grain); the moment
    someone leaks one into the traced step, this trips."""
    import jax

    from tpu_dist.comm import mesh as mesh_lib
    from tpu_dist.resilience import faults

    m = mesh if mesh is not None else mesh_lib.data_parallel_mesh()
    prev = faults.active()
    faults.clear()
    try:
        fn, args = _dp_setup(m)
        base = str(jax.make_jaxpr(fn)(*args))
        faults.install(
            "ckpt_write@call=1:times=2;ckpt_corrupt@epoch=0:mode=bitflip;"
            "nan_loss@step=0;sigterm@step=999999;loader_stall@batch=0;"
            "hang@step=999999:seconds=0.1"
        )
        fn2, args2 = _dp_setup(m)
        armed = str(jax.make_jaxpr(fn2)(*args2))
    finally:
        faults.clear()
        if prev is not None:
            faults.install(prev)
    if base != armed:
        return [
            Violation(
                "TD105",
                "<jaxpr:dp_faults_noop>",
                0,
                "the traced train step CHANGED when a fault plan was armed "
                "— a fault-injection point leaked into the compiled "
                "program; injection must stay host-side "
                "(resilience/faults.py contract)",
                snippet="jaxpr(faults_off) != jaxpr(faults_armed)",
            )
        ]
    return []


def telemetry_noop_violations(mesh=None) -> list[Violation]:
    """TD106: the run-telemetry subsystem's zero-cost contract, checked at
    the program level (the TD105 pattern applied to ``tpu_dist.obs``) —
    trace the data-parallel step with telemetry disarmed, then again with
    the full kit armed (span recorder enabled, counters live and moving, a
    heartbeat beating), and require the two jaxprs to be byte-identical.
    Spans/counters/heartbeat are host-side by construction; the moment an
    instrumentation point leaks a traced op (a timing ``device_get``, a
    counter fed from a tracer), this trips."""
    import os
    import tempfile

    import jax

    from tpu_dist.comm import mesh as mesh_lib
    from tpu_dist.obs import counters, spans
    from tpu_dist.obs.heartbeat import Heartbeat

    m = mesh if mesh is not None else mesh_lib.data_parallel_mesh()
    was_enabled = spans.enabled()
    spans.disable()
    hb_path = None
    try:
        fn, args = _dp_setup(m)
        base = str(jax.make_jaxpr(fn)(*args))
        # arm everything the trainer would arm. fresh=False when a live
        # recorder was already armed: the audit must not wipe its
        # undrained buffer or shift its clock origin. The probe counter
        # and heartbeat beats are honest process telemetry (they record
        # that an audit ran), not pollution to scrub.
        spans.enable(fresh=not was_enabled)
        counters.inc("analysis.td106_probes")
        fd, hb_path = tempfile.mkstemp(suffix=".heartbeat.json")
        os.close(fd)
        hb = Heartbeat(hb_path)
        hb.beat(epoch=0, step=0, force=True)
        with spans.span("td106/trace_probe"):
            fn2, args2 = _dp_setup(m)
            armed = str(jax.make_jaxpr(fn2)(*args2))
        hb.sweep()
    finally:
        if was_enabled:
            # re-arm even when the trace raised BEFORE the enable above —
            # the caller's live recorder must not come back disabled
            # (idempotent when the enable did run)
            spans.enable(fresh=False)
        else:
            spans.disable()
            spans.drain()  # discard the probe's own span events
        if hb_path is not None:
            try:
                os.remove(hb_path)
            except FileNotFoundError:
                pass
    if base != armed:
        return [
            Violation(
                "TD106",
                "<jaxpr:dp_telemetry_noop>",
                0,
                "the traced train step CHANGED when run telemetry was "
                "armed — an instrumentation point leaked into the compiled "
                "program; spans/counters/heartbeat must stay host-side "
                "(tpu_dist.obs contract, docs/observability.md)",
                snippet="jaxpr(telemetry_off) != jaxpr(telemetry_armed)",
            )
        ]
    return []


def device_metrics_noop_violations(mesh=None) -> list[Violation]:
    """TD107: the ``--device_metrics`` cost contract, checked at the
    program level.

    Flag-off half (the TD105/TD106 pattern — armed host machinery vs a
    quiet baseline, NOT two identical traces): the baseline traces the
    default step with nothing armed; then the HOST health layer goes live
    — the compile-time ``jax.monitoring`` listener installed, an
    ``AnomalyDetector`` observing values, a ``CompileWatcher`` reading
    the executable cache — and an explicit ``device_metrics=False`` step
    is traced under it. The two jaxprs must be byte-identical: anomaly
    detection, cost capture, and compile accounting are host-side by
    construction, and the moment someone "optimizes" a threshold or a
    counter into the traced step, this trips.

    Flag-on half: the pure-DP path's collective AND transfer inventories
    must be unchanged — the health scalars are computed on the post-pmean
    gradients and ride the metrics tree the trainer already fetches, so
    the moment one of them needs its own reduce (or a host transfer),
    this trips. The fetch-count half of the contract (still exactly one
    per-step ``device_get``) is a host-loop property, pinned by the
    trainer-level parity test in ``tests/test_device_health.py``."""
    import jax

    from tpu_dist.comm import mesh as mesh_lib
    from tpu_dist.obs import costmodel
    from tpu_dist.obs.anomaly import AnomalyDetector

    m = mesh if mesh is not None else mesh_lib.data_parallel_mesh()
    fn, args = _dp_setup(m)
    base_counts = trace_counts(fn, *args)
    base = str(jax.make_jaxpr(fn)(*args))
    # arm the host health layer, then trace the explicit flag-off step
    costmodel.install_compile_listener()
    det = AnomalyDetector(window=4)
    fn_off, args_off = _dp_setup(m, device_metrics=False)
    watcher = costmodel.CompileWatcher(fn_off)
    for i in range(6):
        det.observe(epoch=0, step=i, loss=1.0 + i, grad_norm=0.5)
        watcher.observe()
    off = str(jax.make_jaxpr(fn_off)(*args_off))
    det.observe(epoch=0, step=99, loss=1e9)  # a firing detector, too
    watcher.observe()
    out: list[Violation] = []
    if base != off:
        out.append(
            Violation(
                "TD107",
                "<jaxpr:dp_device_metrics_noop>",
                0,
                "the traced train step with device_metrics=False under an "
                "armed host health layer (anomaly detector observing, "
                "compile listener + cache watcher live) differs from the "
                "quiet default step — the disabled flag plus the host-side "
                "machinery must be a byte-identical no-op "
                "(obs/device_stats.py contract)",
                snippet="jaxpr(device_metrics_off|health armed) != jaxpr(default)",
            )
        )
    fn_on, args_on = _dp_setup(m, device_metrics=True)
    on_counts = trace_counts(fn_on, *args_on)
    if (
        on_counts["collectives"] != base_counts["collectives"]
        or on_counts["transfers"] != base_counts["transfers"]
    ):
        out.append(
            Violation(
                "TD107",
                "<jaxpr:dp_device_metrics_noop>",
                0,
                "arming --device_metrics changed the pure-DP step's "
                f"collective/transfer inventory (off: "
                f"{base_counts['collectives']}/{base_counts['transfers']} "
                f"→ on: {on_counts['collectives']}/{on_counts['transfers']})"
                " — the health scalars must stay local arithmetic on the "
                "post-pmean gradients",
                snippet=f"collectives:{on_counts['collectives']}",
            )
        )
    return out


def profile_trigger_noop_violations(mesh=None) -> list[Violation]:
    """TD108: the triggered-profiler cost contract, checked at the
    program level (the TD105-TD107 armed-vs-off discipline applied to
    ``obs/profile.py``) — trace the data-parallel step with no profiler,
    then again with a :class:`TriggeredProfiler` ARMED (a health trigger
    has fired, the capture is pending), and again with the capture window
    OPEN (a real ``jax.profiler`` trace in flight), and require all three
    jaxprs to be byte-identical. Arming is host bookkeeping and an open
    window only observes the program XLA already built; the moment
    someone routes a "helpful" marker op or a step-numbering annotation
    through the traced step, this trips."""
    import shutil
    import tempfile

    import jax

    from tpu_dist.comm import mesh as mesh_lib
    from tpu_dist.obs.profile import TriggeredProfiler

    m = mesh if mesh is not None else mesh_lib.data_parallel_mesh()
    fn, args = _dp_setup(m)
    base = str(jax.make_jaxpr(fn)(*args))
    tmp = tempfile.mkdtemp(prefix="td108_profile_")
    out: list[Violation] = []
    try:
        prof = TriggeredProfiler(
            tmp, window_steps=2, cooldown_steps=0, max_captures=1
        )
        prof.arm("anomaly_loss_spike")
        fn2, args2 = _dp_setup(m)
        armed = str(jax.make_jaxpr(fn2)(*args2))
        started = prof.on_step(0)  # opens a REAL device-trace window
        capturing = str(jax.make_jaxpr(fn2)(*args2))
        prof.close()
        # a capture-backend failure (no profiler available here) leaves
        # nothing in flight; the armed comparison above still gates
        capture_ran = bool(started and started.get("event") == "start")
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    if base != armed or (capture_ran and base != capturing):
        out.append(
            Violation(
                "TD108",
                "<jaxpr:dp_profile_trigger_noop>",
                0,
                "the traced train step CHANGED when a profiler trigger "
                "was armed (or a capture window was open) — triggered "
                "profiling must stay control-plane only: host bookkeeping "
                "plus jax.profiler start/stop around the unmodified step "
                "(obs/profile.py contract)",
                snippet="jaxpr(profiler_off) != jaxpr(trigger_armed)",
            )
        )
    return out


def xprof_hook_noop_violations(mesh=None) -> list[Violation]:
    """TD110: the auto-analyze hook's cost contract, checked at the
    program level (the TD105-TD109 armed-vs-off discipline applied to
    ``obs/xprof.py`` via ``obs/profile.py``) — trace the data-parallel
    step with no profiler, then drive a :class:`TriggeredProfiler` whose
    analyze hook is ON through its whole life cycle: armed, capture
    window OPEN (tracing mid-capture), and capture CLOSED — which fires
    the real xprof analysis over the just-written capture directory plus
    the cost-model calibration over its report — and trace again after.
    All four jaxprs must be byte-identical: reading a capture back is
    host-side gzip/JSON crunching, and the moment someone routes a
    "handy" marker op or a calibration probe through the traced step,
    this trips. The probe also asserts the hook actually RAN (a stop
    event carrying ``analysis``/``analysis_error``) when the backend
    could capture — a hook that silently stopped firing would make the
    comparison vacuous."""
    import shutil
    import tempfile

    import jax

    from tpu_dist.comm import mesh as mesh_lib
    from tpu_dist.obs import costmodel
    from tpu_dist.obs.profile import TriggeredProfiler

    m = mesh if mesh is not None else mesh_lib.data_parallel_mesh()
    fn, args = _dp_setup(m)
    base = str(jax.make_jaxpr(fn)(*args))
    tmp = tempfile.mkdtemp(prefix="td110_xprof_")
    out: list[Violation] = []
    try:
        prof = TriggeredProfiler(
            tmp, window_steps=2, cooldown_steps=0, max_captures=1,
            analyze=True,
        )
        prof.arm("anomaly_loss_spike")
        fn2, args2 = _dp_setup(m)
        armed = str(jax.make_jaxpr(fn2)(*args2))
        started = prof.on_step(0)  # opens a REAL device-trace window
        # run real device work inside the window so the capture the hook
        # analyzes holds an actual XLA timeline, not an empty trace
        jax.block_until_ready(jax.jit(lambda x: x * 2.0)(jax.numpy.ones((8,))))
        capturing = str(jax.make_jaxpr(fn2)(*args2))
        stopped = prof.on_step(2)  # closes the window → auto-analysis runs
        capture_ran = bool(started and started.get("event") == "start")
        analysis_ran = bool(
            stopped is not None
            and ("analysis" in stopped or "analysis_error" in stopped)
        )
        if analysis_ran and stopped.get("analysis") is not None:
            # the calibration path is part of the armed hook: fold the
            # measured report into drift gauges exactly as the trainer does
            costmodel.publish_calibration(costmodel.calibration(
                {"flops_per_step": 1e9, "bytes_per_step": 1e6},
                stopped["analysis"], steps=2, n_devices=1, peak=1e12,
            ))
        analyzed = str(jax.make_jaxpr(fn2)(*args2))
        prof.close()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    if capture_ran and not analysis_ran:
        out.append(
            Violation(
                "TD110",
                "<jaxpr:dp_xprof_hook_noop>",
                0,
                "the TD110 probe captured a real profiler window but the "
                "auto-analyze hook produced neither an analysis nor an "
                "analysis_error on the stop event — the armed-vs-off "
                "comparison would be vacuous; the hook stopped firing "
                "(obs/profile.py contract)",
                snippet="auto-analyze hook did not fire",
            )
        )
    if base != armed or (
        capture_ran and (base != capturing or base != analyzed)
    ):
        out.append(
            Violation(
                "TD110",
                "<jaxpr:dp_xprof_hook_noop>",
                0,
                "the traced train step CHANGED across the auto-analyze "
                "hook's life cycle (armed / capture open / capture closed "
                "and analyzed + calibration published) — capture read-back "
                "must stay host-side file crunching (obs/xprof.py + "
                "obs/profile.py contract)",
                snippet="jaxpr(no_profiler) != jaxpr(xprof_hook_armed)",
            )
        )
    return out


def flight_recorder_noop_violations(mesh=None) -> list[Violation]:
    """TD113: the crash-forensics cost contract, checked at the program
    level (the TD105-TD112 armed-vs-off discipline applied to
    ``obs/flight.py``) — trace the data-parallel step with nothing
    armed, then arm the FULL forensic kit exactly as ``fit()`` does:
    a :class:`FlightRecorder` writing real ring slots (open + step
    records with counter deltas), the ``sys``/``threading`` excepthook
    wrappers installed, the span-open listener tapping the ring, and
    ``faulthandler`` armed to a crash file with the SIGUSR1 all-threads
    dump registered AND actually fired mid-audit — and trace again. The
    two jaxprs must be byte-identical: forensics is pwrite-at-the-step-
    boundary host I/O, and the moment someone routes a step marker or a
    'helpful' device sync through the traced step, this trips. The
    probe also asserts the kit actually RAN (the ring decodes with
    records; the dump file holds a parseable traceback when the signal
    could be delivered) — a dead recorder would make the comparison
    vacuous."""
    import os
    import shutil
    import signal
    import tempfile

    import jax

    from tpu_dist.comm import mesh as mesh_lib
    from tpu_dist.obs import flight as flight_lib
    from tpu_dist.obs import spans

    m = mesh if mesh is not None else mesh_lib.data_parallel_mesh()
    fn, args = _dp_setup(m)
    base = str(jax.make_jaxpr(fn)(*args))
    tmp = tempfile.mkdtemp(prefix="td113_flight_")
    rec = None
    handle = None
    out: list[Violation] = []
    try:
        rec = flight_lib.FlightRecorder(
            os.path.join(tmp, flight_lib.RING_NAME), run_id="td113", rank=0
        )
        rec.install_excepthooks()
        spans.set_open_listener(rec.span_open)
        rec.record("open", world=1)
        handle = flight_lib.arm_faulthandler(
            os.path.join(tmp, flight_lib.STACKS_NAME)
        )
        dumped = False
        if handle is not None and handle.registered:
            os.kill(os.getpid(), signal.SIGUSR1)  # a REAL on-demand dump
            dumped = True
        rec.step(0, 0)
        with spans.span("td113/trace_probe"):
            fn2, args2 = _dp_setup(m)
            armed = str(jax.make_jaxpr(fn2)(*args2))
        rec.step(0, 1)
        ring_path = rec.path
        stacks_path = os.path.join(tmp, flight_lib.STACKS_NAME)
        decoded = flight_lib.decode(ring_path)
        ring_ok = len(decoded["records"]) >= 3 and not decoded["torn_slots"]
        dump_ok = True
        if dumped:
            parsed = flight_lib.read_stack_dump(stacks_path)
            dump_ok = bool(parsed and parsed.get("current"))
    finally:
        spans.clear_open_listener()
        if rec is not None:
            rec.uninstall_excepthooks()
            rec.close()
        if handle is not None:
            flight_lib.disarm_faulthandler(handle)
        shutil.rmtree(tmp, ignore_errors=True)
    if not ring_ok or not dump_ok:
        out.append(
            Violation(
                "TD113",
                "<jaxpr:dp_flight_recorder_noop>",
                0,
                "the TD113 probe armed the forensic kit but it did not "
                "actually run ("
                + ("ring failed to decode its own records" if not ring_ok
                   else "the SIGUSR1 dump produced no parseable "
                        "traceback")
                + ") — the armed-vs-off comparison would be vacuous "
                "(obs/flight.py contract)",
                snippet="flight probe did not fire",
            )
        )
    if base != armed:
        out.append(
            Violation(
                "TD113",
                "<jaxpr:dp_flight_recorder_noop>",
                0,
                "the traced train step CHANGED when crash forensics was "
                "armed (flight ring writing, excepthooks wrapped, span "
                "listener tapped, faulthandler + SIGUSR1 dump live) — "
                "forensics must stay host-side file I/O on the step "
                "boundary (obs/flight.py contract, docs/observability.md "
                "'Crash forensics')",
                snippet="jaxpr(forensics_off) != jaxpr(forensics_armed)",
            )
        )
    return out


def live_export_noop_violations(mesh=None) -> list[Violation]:
    """TD109: the live-telemetry cost contract, checked at the program
    level (the TD105-TD108 armed-vs-off discipline applied to
    ``obs/export.py`` + ``obs/alerts.py``) — trace the data-parallel
    step with nothing armed, then arm the FULL live kit: a
    :class:`MetricsExporter` with a real textfile AND a live HTTP
    ``/metrics`` thread serving scrapes, fed a real exposition, plus an
    :class:`AlertEngine` over the built-in rule library observing
    windows and actually FIRING (a sustained stall-fraction breach, the
    exact acceptance scenario) — and trace again. The two jaxprs must be
    byte-identical: exporting and alerting are host-side string/float
    work on values the trainer already holds, and the moment someone
    routes a threshold check or a gauge through the traced step, this
    trips."""
    import os
    import shutil
    import tempfile
    import urllib.request

    import jax

    from tpu_dist.comm import mesh as mesh_lib
    from tpu_dist.obs import alerts as alerts_lib
    from tpu_dist.obs.export import MetricsExporter

    m = mesh if mesh is not None else mesh_lib.data_parallel_mesh()
    fn, args = _dp_setup(m)
    base = str(jax.make_jaxpr(fn)(*args))
    tmp = tempfile.mkdtemp(prefix="td109_export_")
    exporter = None
    try:
        engine = alerts_lib.AlertEngine(alerts_lib.load_rules("default"))
        # sustain the stall-frac breach until the rule FIRES — the engine
        # under test must be in its fired state, not just constructed
        fired = []
        for _ in range(3):
            fired.extend(engine.observe({"data_stall_frac": 0.9, "mfu": 0.8}))
        try:
            exporter = MetricsExporter(
                textfile=os.path.join(tmp, "metrics.prom"), port=0, rank=0
            )
        except OSError:
            # no socket allowed in this sandbox: the textfile half still
            # arms; the scrape below just won't run
            exporter = MetricsExporter(
                textfile=os.path.join(tmp, "metrics.prom"), rank=0
            )
        exporter.update(
            {"train.data_stall_frac": 0.9, "train.steps": 42},
            {"alert_active": engine.active()},
            force=True,
        )
        if exporter.port:
            # a live scrape against the serving thread, mid-audit
            with urllib.request.urlopen(
                f"http://127.0.0.1:{exporter.port}/metrics", timeout=5
            ) as resp:
                resp.read()
        fn2, args2 = _dp_setup(m)
        armed = str(jax.make_jaxpr(fn2)(*args2))
        probe_ok = bool(fired) and bool(engine.active().get("stall_high"))
    finally:
        if exporter is not None:
            exporter.close()
        shutil.rmtree(tmp, ignore_errors=True)
    out: list[Violation] = []
    if not probe_ok:
        out.append(
            Violation(
                "TD109",
                "<jaxpr:dp_live_export_noop>",
                0,
                "the TD109 probe could not put the alert engine into its "
                "fired state (the built-in stall_high rule did not fire "
                "on a sustained breach) — the armed-vs-off comparison "
                "would be vacuous; the alert state machine drifted",
                snippet="alert probe did not fire",
            )
        )
    if base != armed:
        out.append(
            Violation(
                "TD109",
                "<jaxpr:dp_live_export_noop>",
                0,
                "the traced train step CHANGED when the live exporter + "
                "alert engine were armed (exposition published, HTTP "
                "endpoint scraped, rules fired) — live telemetry must "
                "stay host-side (obs/export.py + obs/alerts.py contract, "
                "docs/observability.md)",
                snippet="jaxpr(live_off) != jaxpr(live_armed)",
            )
        )
    return out


def _elastic_noop_probe(mesh=None, *, grow: bool):
    """Shared TD111/TD112 probe machinery: build the OLD world's ZeRO-1 +
    error-feedback state host-side (flat momentum padded for ``n_old``
    devices, ``n_old`` residual rows), save a real checkpoint, restore it
    through the elastic remapper onto a template laid out for ``n_new``
    devices, and trace the ``n_new`` train step with the fresh-start
    state and with the restored one. ``grow=False`` is the TD111 shrink
    direction (``n_old = all devices, n_new = n_old // 2``); ``grow=True``
    mirrors it (``n_old = n // 2, n_new = n`` — the path a probe-triggered
    scale-up or fleet chip receipt resumes through).

    The probe model's raveled length is congruent to 4 mod 8 precisely so
    the extent change reshapes the padded flat layouts (the default audit
    MLP's 480 divides every mesh width, which would make the remap a
    no-op). Returns ``(layouts_differ, remapper_fired, identical)``."""
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_dist.ckpt import checkpoint as ckpt_lib
    from tpu_dist.comm import mesh as mesh_lib
    from tpu_dist.comm.quantize import padded_len
    from tpu_dist.elastic.remap import Remapper, params_len
    from tpu_dist.train.optim import SGD
    from tpu_dist.train.state import TrainState
    from tpu_dist.train.step import (
        ef_state_host_zeros,
        init_ef_state,
        init_sharded_opt_state,
        make_train_step,
    )

    devs = (
        list(mesh.devices.ravel()) if mesh is not None else jax.devices()
    )
    if grow:
        n_new = len(devs)
        n_old = max(1, n_new // 2)
    else:
        n_old = len(devs)
        n_new = max(1, n_old // 2)
    mesh_new = mesh_lib.data_parallel_mesh(devs[:n_new])

    class _ElasticMLP(_AuditMLP):
        # classes=12 -> L = 12*16 + 16 + 16*12 + 12 = 412 == 4 (mod 8):
        # padded_len(412, 8) = 416 != 412 = padded_len(412, 4) — the
        # extent change genuinely reshapes the flat layouts
        classes = 12

    model = _ElasticMLP()
    params, bn = model.init(jax.random.PRNGKey(0))
    L = params_len(params)
    params_host = jax.tree_util.tree_map(np.asarray, params)
    mom_old = np.zeros((padded_len(L, n_old),), np.float32)
    mom_old[:L] = np.arange(L, dtype=np.float32) * 1e-3
    ef_old = ef_state_host_zeros(params_host, n_old, zero1=True)
    ef_old = {
        "r1": (np.arange(ef_old["r1"].size) * 1e-6).astype(np.float32)
    }
    st_old = TrainState(
        params_host, {}, mom_old, np.asarray(0, np.int32), ef_old
    )
    tmp = tempfile.mkdtemp(prefix="td112_grow_" if grow else "td111_elastic_")
    try:
        path = ckpt_lib.save(tmp, st_old, epoch=0)
        opt = SGD(momentum=0.9, weight_decay=1e-4)
        state_new = TrainState(
            params, bn,
            init_sharded_opt_state(params, mesh_new),
            jnp.zeros((), jnp.int32),
            init_ef_state(params, mesh_new, zero1=True),
        )
        step = make_train_step(
            model.apply, opt, mesh_new, sync_bn=False,
            shard_weight_update=True, grad_compression="int8_ef",
        )
        b = 8 * n_new
        images = jax.ShapeDtypeStruct((b, 2, 2, 3), jnp.float32)
        labels = jax.ShapeDtypeStruct((b,), jnp.int32)
        lr = jax.ShapeDtypeStruct((), jnp.float32)
        base = str(jax.make_jaxpr(step)(state_new, images, labels, lr))
        remapper = Remapper(L, n_new, n_old=n_old)
        restored = ckpt_lib.restore(path, state_new, remap=remapper)
        resumed = str(jax.make_jaxpr(step)(restored, images, labels, lr))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    layouts_differ = (
        padded_len(L, n_old) != padded_len(L, n_new) or n_old != n_new
    )
    return layouts_differ, bool(remapper.used), base == resumed


def elastic_resume_noop_violations(mesh=None) -> list[Violation]:
    """TD111: elastic resume must be invisible to the compiled program —
    a trainer whose state was RESTORED from a checkpoint written at a
    different dp extent (and remapped by ``tpu_dist/elastic/remap.py``)
    must trace the byte-identical step a fresh-start trainer at the same
    (new) world size traces. Any remap sloppiness — a float64 leak from
    numpy padding, a wrong flat length, a dtype drift — changes the
    avals and trips this; and the probe asserts the remapper actually
    FIRED when the two extents produce different padded lengths (a
    vacuous comparison is itself a violation). Probe machinery shared
    with TD112: :func:`_elastic_noop_probe` (the shrink direction)."""
    layouts_differ, fired, identical = _elastic_noop_probe(mesh, grow=False)
    out: list[Violation] = []
    if layouts_differ and not fired:
        out.append(
            Violation(
                "TD111",
                "<jaxpr:dp_elastic_resume_noop>",
                0,
                "the TD111 probe restored across different dp extents but "
                "the elastic remapper never fired — the armed-vs-fresh "
                "comparison would be vacuous; the restore path stopped "
                "routing shape mismatches through the remap hook",
                snippet="elastic remapper did not fire",
            )
        )
    if not identical:
        out.append(
            Violation(
                "TD111",
                "<jaxpr:dp_elastic_resume_noop>",
                0,
                "the traced train step of an elastic-resumed trainer "
                "differs from a fresh-start trainer at the same (new) "
                "world size — the checkpoint remap leaked into the "
                "compiled program (shape/dtype drift in the remapped "
                "ZeRO-1/EF flat layouts; tpu_dist/elastic/remap.py "
                "contract)",
                snippet="jaxpr(fresh_start) != jaxpr(elastic_resumed)",
            )
        )
    return out


def elastic_grow_noop_violations(mesh=None) -> list[Violation]:
    """TD112: the grow mirror of TD111 — a trainer whose state was
    RESTORED from a checkpoint written at a SMALLER dp extent (saved at
    ``n_old = n_new // 2`` and remapped UP) must trace the byte-identical
    step a fresh-start trainer at the larger world size traces. This is
    the proof the scale-up path rides on (docs/resilience.md "Scale-up &
    fleet scheduling"): the supervisor's probe-triggered grow and the
    fleet scheduler's chip receipts both relaunch ``--resume`` onto MORE
    devices, so the remapper's zero-repad of the ZeRO-1 flat vectors,
    the r1 fold into more replica rows, and the r2 re-pad must reproduce
    exactly the aval layout a fresh construction gets. Probe machinery
    shared with TD111: :func:`_elastic_noop_probe` (extents swapped)."""
    layouts_differ, fired, identical = _elastic_noop_probe(mesh, grow=True)
    out: list[Violation] = []
    if layouts_differ and not fired:
        out.append(
            Violation(
                "TD112",
                "<jaxpr:dp_elastic_grow_noop>",
                0,
                "the TD112 probe restored a smaller-world checkpoint "
                "onto more devices but the elastic remapper never fired "
                "— the armed-vs-fresh comparison would be vacuous; the "
                "restore path stopped routing grow shape mismatches "
                "through the remap hook",
                snippet="elastic grow remapper did not fire",
            )
        )
    if not identical:
        out.append(
            Violation(
                "TD112",
                "<jaxpr:dp_elastic_grow_noop>",
                0,
                "the traced train step of a GROW-resumed trainer (state "
                "saved at a smaller dp extent, remapped up) differs from "
                "a fresh-start trainer at the same larger world size — "
                "the scale-up remap leaked into the compiled program "
                "(shape/dtype drift in the re-laid ZeRO-1/EF flat "
                "layouts; tpu_dist/elastic/remap.py contract)",
                snippet="jaxpr(fresh_start) != jaxpr(grow_resumed)",
            )
        )
    return out


def serving_slo_noop_violations(mesh=None) -> list[Violation]:
    """TD114: the serving observability cost contract, checked at the
    program level (the TD105-TD113 armed-vs-off discipline applied to
    ``tpu_dist/serve``) — trace the bare inference forward step (the
    audit MLP's eval-mode apply on one batch bucket), then arm the FULL
    serve telemetry/SLO kit exactly as the engine's pump loop does:
    streaming latency histograms observing real per-phase samples,
    queue/occupancy/availability gauges published into the registry, the
    SLO alert engine driven into a FIRED state (a breached p99 ceiling
    and a blown deadline), the OpenMetrics histogram exposition rendered
    AND parsed back, and a span open around the re-trace — and trace
    again. The two jaxprs must be byte-identical: serving SLOs are host
    arithmetic on timestamps the pump already takes, and the moment
    someone routes a latency probe or a 'helpful' sync through the
    compiled step, this trips. The probe also asserts the kit actually
    RAN (histograms hold samples, a rule fired, the exposition
    round-trips the count) — a dead stats object would make the
    comparison vacuous."""
    import jax
    import jax.numpy as jnp

    from tpu_dist.obs import counters as counters_lib
    from tpu_dist.obs import export as export_lib
    from tpu_dist.obs import spans
    from tpu_dist.serve import slo as slo_lib

    model = _AuditMLP()
    params, bn = model.init(jax.random.PRNGKey(0))
    x = jax.ShapeDtypeStruct((8, 2, 2, 3), jnp.float32)

    def forward(p, s, images):
        logits, _ = model.apply(p, s, images, train=False)
        return logits

    base = str(jax.make_jaxpr(forward)(params, bn, x))

    stats = slo_lib.ServeStats(deadline_s=0.05)
    engine = slo_lib.make_slo_engine(slo_lib.load_slo_rules("default"))
    fired: list = []
    for _ in range(3):  # 3 windows: sustain=2 rules genuinely sustain
        for _ in range(4):
            stats.on_batch(3, 4)
            # 600 ms total: breaches the 500 ms slo_p99_high ceiling AND
            # the 50 ms probe deadline (availability 0 < 0.999)
            stats.on_request_done(
                0.6, 0.45, {p: 0.1 for p in slo_lib.PHASES}
            )
        stats.set_queue_depth(2)
        window = stats.scalars(window_s=1.0, completed_in_window=4)
        stats.publish(window)
        fired.extend(engine.observe(window))
    exposition = export_lib.render(
        counters_lib.snapshot(),
        {"alert_active": engine.active()},
        histograms=stats.histogram_families(),
    )
    parsed = export_lib.parse(exposition)
    count_key = export_lib.metric_name("serve.latency_seconds") + "_count"
    with spans.span("td114/trace_probe"):
        armed = str(jax.make_jaxpr(forward)(params, bn, x))

    out: list[Violation] = []
    ran = (
        stats.total.count == 12
        and not stats.check_invariants()
        and fired
        and parsed.get(count_key) == 12
    )
    if not ran:
        out.append(
            Violation(
                "TD114",
                "<jaxpr:serving_slo_noop>",
                0,
                "the TD114 probe armed the serve SLO kit but it did not "
                "actually run (histograms empty, invariants broken, no "
                "rule fired, or the exposition failed to round-trip) — "
                "the armed-vs-off comparison would be vacuous "
                "(tpu_dist/serve/slo.py contract)",
                snippet="serve slo probe did not fire",
            )
        )
    if base != armed:
        out.append(
            Violation(
                "TD114",
                "<jaxpr:serving_slo_noop>",
                0,
                "the traced serving forward step CHANGED when the serve "
                "telemetry/SLO machinery was armed (latency histograms "
                "observing, gauges published, SLO rules fired, histogram "
                "exposition rendered, span open) — serving observability "
                "must stay host-side arithmetic around the unmodified "
                "compiled step (tpu_dist/serve contract, docs/serving.md)",
                snippet="jaxpr(bare_inference) != jaxpr(slo_armed)",
            )
        )
    return out


#: A canned TPU-style RESOURCE_EXHAUSTED text the TD115 probe parses —
#: arming the OOM parser is part of the memory kit under audit.
_TD115_OOM_TEXT = (
    "RESOURCE_EXHAUSTED: Ran out of memory in memory space hbm. "
    "Used 15.90G of 15.48G hbm. Exceeded hbm capacity by 430.5M.\n"
    "Largest program allocations in hbm:\n"
    "  1. Size: 2.50G\n"
    '     Operator: op_name="jit(train_step)/dot_general"\n'
    "     Shape: f32[8192,81920]\n"
)


def memory_ledger_noop_violations(mesh=None) -> list[Violation]:
    """TD115: the HBM-observability cost contract, checked at the
    program level (the TD105-TD114 armed-vs-off discipline applied to
    ``obs/memory.py``) — trace the data-parallel step with nothing
    armed, then arm the FULL memory kit exactly as the trainer does:
    the static per-leaf ledger over a real ZeRO-1-sharded state
    (sharded-extent accounting from shardings), the live-buffer census
    over ``jax.live_arrays()``, the allocator ``memory_stats()`` read,
    the census/allocator reconciliation, the ``mem.*`` gauges
    published, the pre-flight feasibility check priced against a real
    budget, the ``memory_analysis()`` waterfall of an AOT-compiled
    probe, and the RESOURCE_EXHAUSTED parser over a canned TPU OOM
    text — and trace again. The two jaxprs must be byte-identical:
    the whole ledger is shape/sharding metadata arithmetic, and the
    moment someone routes a byte-counting probe or a 'helpful' sync
    through the traced step, this trips. The probe also asserts the
    kit actually RAN (non-empty ledger, the reconciliation identity
    holding exactly, a parsed OOM report with the right byte counts) —
    a dead ledger would make the comparison vacuous."""
    import jax
    import jax.numpy as jnp

    from tpu_dist.comm import mesh as mesh_lib
    from tpu_dist.obs import costmodel
    from tpu_dist.obs import memory as memory_lib

    m = mesh if mesh is not None else mesh_lib.data_parallel_mesh()
    # ZeRO-1 case: the state's flat momentum is genuinely sharded, so
    # the ledger's sharded-extent accounting is exercised, not skipped
    fn, args = _dp_setup(m, shard_weight_update=True)
    state = args[0]
    base = str(jax.make_jaxpr(fn)(*args))

    led = memory_lib.static_ledger(
        params=state.params, opt_state=state.opt_state, ef=state.ef,
        bn_state=state.bn_state,
    )
    census = memory_lib.live_census()
    rec = memory_lib.reconcile(census, costmodel.device_memory_stats())
    memory_lib.publish_ledger({
        "static": led, "census": census, "reconciliation": rec,
    })
    feas = memory_lib.feasibility(
        led["bytes_per_device"], budget_bytes=16 * 1024 ** 3, headroom=0.9,
    )
    probe = jax.jit(lambda x: x * 2.0)
    xla = costmodel.memory_analysis_jitted(probe, jnp.ones((64,)))
    oom = memory_lib.parse_resource_exhausted(_TD115_OOM_TEXT)

    fn2, args2 = _dp_setup(m, shard_weight_update=True)
    armed = str(jax.make_jaxpr(fn2)(*args2))

    n = m.devices.size
    ran = (
        led["bytes_per_device"] > 0
        and led["sections"]["opt_state"]["bytes_per_device"] > 0
        and (
            n == 1
            or led["sections"]["opt_state"]["sharded_leaves"] > 0
        )
        and census["n_arrays"] > 0
        and rec["attributed_bytes"] + rec["unattributed_bytes"]
        == rec["bytes_in_use"]
        and feas["fits"]
        and oom is not None
        and oom.get("used_bytes") == int(15.90 * 1024 ** 3)
        and len(oom.get("buffers") or []) == 1
    )
    out: list[Violation] = []
    if not ran:
        out.append(
            Violation(
                "TD115",
                "<jaxpr:dp_memory_ledger_noop>",
                0,
                "the TD115 probe armed the HBM ledger kit but it did "
                "not actually run (empty ledger, no sharded-extent "
                "accounting, broken reconciliation identity, or the "
                "OOM parser returned garbage) — the armed-vs-off "
                "comparison would be vacuous (obs/memory.py contract)",
                snippet="memory ledger probe did not fire",
            )
        )
    if base != armed:
        out.append(
            Violation(
                "TD115",
                "<jaxpr:dp_memory_ledger_noop>",
                0,
                "the traced train step CHANGED when the HBM ledger was "
                "armed (static per-leaf accounting, live census, "
                "allocator reconciliation, gauges, feasibility check, "
                "memory_analysis waterfall, OOM parser) — memory "
                "observability must stay host-side metadata arithmetic "
                "(obs/memory.py contract, docs/observability.md "
                "'HBM ledger & OOM forensics')",
                snippet="jaxpr(ledger_off) != jaxpr(ledger_armed)",
            )
        )
    return out


def tenancy_arbitration_noop_violations(mesh=None) -> list[Violation]:
    """TD122: the multi-tenancy cost contract, checked at the program
    level (the TD105-TD121 armed-vs-off discipline applied to the
    train+serve co-scheduling plane) — trace the data-parallel train
    step AND the serving forward step with nothing armed, then arm the
    FULL tenancy kit exactly as a co-scheduled pod runs it: a breached
    serve exposition (fired ``slo_*`` alerts, queue/availability/p99
    gauges, latency histograms) rendered to disk and scraped back
    through the fleet sensor path (``read_signals``), a kind-aware
    :class:`FleetScheduler` driven through a SUSTAINED breach to a
    genuinely fired ``preempt=True`` donate→grant pair, the cooperative
    SIGTERM flag raised through the installed handler, a live
    :class:`ServingEngine` refusing work under shedding admission, and
    the per-tick chip-second conservation audit — and trace both steps
    again WHILE the preemption flag is up and shedding is on. Both
    jaxprs must be byte-identical: arbitration is host arithmetic over
    scraped files and allocation integers, and the moment someone
    routes a preemption check or an SLO probe through a compiled step,
    this trips. The probe also asserts the kit actually RAN (the scrape
    round-tripped the serve gauges, the preemption decision fired and
    the chips landed, the flag was observed, a request was actually
    shed, the chip-second books balance exactly) — a dead arbiter would
    make the comparison vacuous."""
    import os
    import signal as signal_lib
    import tempfile

    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_dist.comm import mesh as mesh_lib
    from tpu_dist.fleet import scheduler as fleet_lib
    from tpu_dist.obs import export as export_lib
    from tpu_dist.obs import heartbeat as heartbeat_lib
    from tpu_dist.resilience import preemption
    from tpu_dist.serve import slo as slo_lib
    from tpu_dist.serve.engine import ServingEngine

    m = mesh if mesh is not None else mesh_lib.data_parallel_mesh()
    fn, args = _dp_setup(m, shard_weight_update=True)
    base_train = str(jax.make_jaxpr(fn)(*args))

    model = _AuditMLP()
    params, bn = model.init(jax.random.PRNGKey(0))
    x = jax.ShapeDtypeStruct((8, 2, 2, 3), jnp.float32)

    def forward(p, s, images):
        logits, _ = model.apply(p, s, images, train=False)
        return logits

    base_serve = str(jax.make_jaxpr(forward)(params, bn, x))

    # -- arm: a genuinely breached serve run, scraped off disk --------------
    stats = slo_lib.ServeStats(deadline_s=0.05)
    slo_engine = slo_lib.make_slo_engine(slo_lib.load_slo_rules("default"))
    fired: list = []
    window: dict = {}
    for _ in range(3):  # sustain=2 rules genuinely sustain
        for _ in range(4):
            stats.on_batch(3, 4)
            # 600 ms: breaches slo_p99_high AND the 50 ms deadline
            stats.on_request_done(
                0.6, 0.45, {p: 0.1 for p in slo_lib.PHASES}
            )
        stats.set_queue_depth(6)
        window = stats.scalars(window_s=1.0, completed_in_window=4)
        fired.extend(slo_engine.observe(window))
    with tempfile.TemporaryDirectory(prefix="td122_") as td:
        prom = os.path.join(td, "metrics.prom")
        with open(prom, "w") as f:
            f.write(export_lib.render(
                window,
                {"alert_active": slo_engine.active()},
                histograms=stats.histogram_families(),
            ))
        hb_path = os.path.join(td, "hb.json")
        heartbeat_lib.Heartbeat(hb_path).beat(force=True)
        sig = fleet_lib.read_signals("svc", prom, heartbeat_file=hb_path)

    # -- arm: the kind-aware arbiter, driven to a fired preemption ----------
    sched = fleet_lib.FleetScheduler(
        [
            fleet_lib.RunSpec("trainer", 8, min_procs=2, kind="train"),
            fleet_lib.RunSpec("svc", 4, min_procs=1, kind="serve"),
        ],
        allocations={"trainer": 8, "svc": 2},
    )
    signals = {
        "trainer": fleet_lib.RunSignals(
            run="trainer", data_stall_frac=0.02, goodput_frac=0.9,
            alive=True,
        ),
        "svc": sig,
    }
    decisions: list = []
    tenancy: list = []
    for t in range(1, 5):
        decisions.extend(sched.step(t, signals))
        tenancy.append(sched.tenancy_record(t))
    audit = fleet_lib.audit_chip_seconds(tenancy)

    # -- arm: the cooperative SIGTERM flag + shedding admission -------------
    token = preemption.install()
    engine = ServingEngine(model, params, bn, max_batch=4, max_queue=2)
    try:
        if signal_lib.getsignal(signal_lib.SIGTERM) is preemption._handler:
            signal_lib.raise_signal(signal_lib.SIGTERM)
        else:  # audit driven off the main thread: no handler installed
            preemption._handler(signal_lib.SIGTERM, None)
        flag_fired = preemption.requested()
        engine.set_shedding(True, "vacate (TD122 probe)")
        refused = engine.submit(np.zeros((2, 2, 3), np.float32))
        shed_ok = (
            not refused.ok
            and engine.stats.shed == 1
            and engine.queue_depth() == 0
        )
        # re-trace with the WHOLE kit up: flag raised, shedding on,
        # arbiter holding post-preemption state
        fn2, args2 = _dp_setup(m, shard_weight_update=True)
        armed_train = str(jax.make_jaxpr(fn2)(*args2))
        armed_serve = str(jax.make_jaxpr(forward)(params, bn, x))
    finally:
        engine.set_shedding(False)
        preemption.clear()
        preemption.restore(token)

    out: list[Violation] = []
    ran = (
        sig.queue_depth == 6.0
        and sig.alive is True
        and any(a.startswith("slo_") for a in sig.active_alerts)
        and fired
        and any(d.get("preempt") for d in decisions)
        and sched.preemptions >= 2  # the donate AND the grant
        and sched.alloc == {"trainer": 4, "svc": 4}
        and flag_fired
        and shed_ok
        and audit["conserved"]
    )
    if not ran:
        out.append(
            Violation(
                "TD122",
                "<jaxpr:tenancy_arbitration_noop>",
                0,
                "the TD122 probe armed the tenancy arbitration kit but "
                "it did not actually run (serve gauges failed to scrape, "
                "no slo_* alert fired, the preemption decision never "
                "fired or the chips never landed, the SIGTERM flag was "
                "not observed, no request was shed, or the chip-second "
                "books failed to balance) — the armed-vs-off comparison "
                "would be vacuous (tpu_dist/fleet/scheduler.py contract)",
                snippet="tenancy arbitration probe did not fire",
            )
        )
    if base_train != armed_train:
        out.append(
            Violation(
                "TD122",
                "<jaxpr:tenancy_arbitration_noop>",
                0,
                "the traced train step CHANGED when the multi-tenant "
                "arbitration kit was armed (serve scrape, kind-aware "
                "policy, fired preemption, SIGTERM flag, shedding "
                "admission) — co-scheduling must stay host-side control-"
                "plane arithmetic around the unmodified compiled step "
                "(tpu_dist/fleet/scheduler.py contract, "
                "docs/resilience.md 'Multi-tenant pod')",
                snippet="jaxpr(train, tenancy_off) != jaxpr(train, tenancy_armed)",
            )
        )
    if base_serve != armed_serve:
        out.append(
            Violation(
                "TD122",
                "<jaxpr:tenancy_arbitration_noop>",
                0,
                "the traced serving forward step CHANGED when the multi-"
                "tenant arbitration kit was armed — a replica under an "
                "active vacate (flag up, shedding on) must serve the "
                "SAME compiled program it warmed, or the drain window "
                "retraces exactly when latency matters most "
                "(tpu_dist/serve/engine.py contract, docs/serving.md)",
                snippet="jaxpr(serve, tenancy_off) != jaxpr(serve, tenancy_armed)",
            )
        )
    return out


def pod_hub_noop_violations(mesh=None) -> list[Violation]:
    """TD123: the pod telemetry plane cost contract — trace the
    data-parallel train step AND the serving forward step with nothing
    armed, then arm the FULL telemetry plane exactly as a co-scheduled
    pod runs it: two live run expositions (a healthy trainer, a
    genuinely breached serve run) federated through ONE
    :class:`TelemetryHub` pass with the fleet scheduler's own
    exposition feeding the chip rollups, the arbiter consuming THAT
    snapshot (``signals_from_hub`` — the one fan-in) and driven through
    a sustained breach to a genuinely fired donate→grant pair SHARING
    one ``decision_id``, the id read back off the allocation file,
    stamped into a relaunch env by the supervisor helper, propagated
    into a resume record, and charged by the goodput ledger to
    ``preempt_for_serve_s`` with the bucket partition still exact —
    and trace both steps again mid-audit. Both jaxprs must be
    byte-identical: federation, causal tracing, and attribution are
    host-side file arithmetic, and the moment someone routes a hub
    scrape or a decision-id check through a compiled step, this trips.
    The probe also asserts the plane actually RAN (two runs aggregated,
    the federated page round-trips, the chain holds ONE id across every
    artifact layer, the ledger partition is exact) — zero runs
    aggregated or a chain with no propagated id is itself a
    violation."""
    import os
    import tempfile

    import jax
    import jax.numpy as jnp

    from tpu_dist.comm import mesh as mesh_lib
    from tpu_dist.elastic import supervisor as supervisor_lib
    from tpu_dist.fleet import capacity as capacity_lib
    from tpu_dist.fleet import scheduler as fleet_lib
    from tpu_dist.obs import export as export_lib
    from tpu_dist.obs import goodput as goodput_lib
    from tpu_dist.obs import heartbeat as heartbeat_lib
    from tpu_dist.obs import hub as hub_lib
    from tpu_dist.serve import slo as slo_lib

    m = mesh if mesh is not None else mesh_lib.data_parallel_mesh()
    fn, args = _dp_setup(m, shard_weight_update=True)
    base_train = str(jax.make_jaxpr(fn)(*args))

    model = _AuditMLP()
    params, bn = model.init(jax.random.PRNGKey(0))
    x = jax.ShapeDtypeStruct((8, 2, 2, 3), jnp.float32)

    def forward(p, s, images):
        logits, _ = model.apply(p, s, images, train=False)
        return logits

    base_serve = str(jax.make_jaxpr(forward)(params, bn, x))

    with tempfile.TemporaryDirectory(prefix="td123_") as td:
        # -- arm: two live runs, one healthy and one breached ---------------
        train_prom = os.path.join(td, "trainer.prom")
        with open(train_prom, "w") as f:
            f.write(export_lib.render({
                "train.data_stall_frac": 0.02,
                "goodput.goodput_frac": 0.9,
            }))
        train_hb = os.path.join(td, "trainer.hb")
        heartbeat_lib.Heartbeat(train_hb).beat(force=True)

        stats = slo_lib.ServeStats(deadline_s=0.05)
        slo_engine = slo_lib.make_slo_engine(slo_lib.load_slo_rules("default"))
        window: dict = {}
        for _ in range(3):  # sustain=2 rules genuinely sustain
            for _ in range(4):
                stats.on_batch(3, 4)
                stats.on_request_done(
                    0.6, 0.45, {p: 0.1 for p in slo_lib.PHASES}
                )
            stats.set_queue_depth(6)
            window = stats.scalars(window_s=1.0, completed_in_window=4)
            slo_engine.observe(window)
        svc_prom = os.path.join(td, "svc.prom")
        with open(svc_prom, "w") as f:
            f.write(export_lib.render(
                window,
                {"alert_active": slo_engine.active()},
                histograms=stats.histogram_families(),
            ))
        svc_hb = os.path.join(td, "svc.hb")
        heartbeat_lib.Heartbeat(svc_hb).beat(force=True)

        # -- arm: hub-fed arbiter driven to a chained donate→grant ----------
        fleet_prom = os.path.join(td, "fleet.prom")
        sched = fleet_lib.FleetScheduler(
            [
                fleet_lib.RunSpec("trainer", 8, min_procs=2, kind="train"),
                fleet_lib.RunSpec("svc", 4, min_procs=1, kind="serve"),
            ],
            allocations={"trainer": 8, "svc": 2},
            fleet_dir=td,
        )
        hub = hub_lib.TelemetryHub(
            [
                hub_lib.RunSource(
                    "trainer", metrics_file=train_prom,
                    heartbeat_file=train_hb, kind="train",
                ),
                hub_lib.RunSource(
                    "svc", metrics_file=svc_prom,
                    heartbeat_file=svc_hb, kind="serve",
                ),
            ],
            fleet_exposition=fleet_prom,
        )
        decisions: list = []
        snap: dict = {}
        for t in range(1, 5):
            sched.write_exposition(fleet_prom)
            snap = hub.collect()
            decisions.extend(
                sched.step(t, fleet_lib.signals_from_hub(snap))
            )
        sched.write_exposition(fleet_prom)
        snap = hub.collect()  # scraped MID-AUDIT, post-preemption state
        federated = hub.federated(snap)

        # -- arm: the id crossing every artifact layer ----------------------
        donate = next(
            (d for d in decisions if d.get("action") == "donate"), {}
        )
        grant = next(
            (d for d in decisions if d.get("action") == "grant"
             and d.get("chained")), {}
        )
        did = donate.get("decision_id")
        alloc_meta = capacity_lib.read_allocation_meta(
            sched.allocation_path("trainer")
        )
        env: dict = {}
        supervisor_lib.stamp_decision_env(
            env, sched.allocation_path("trainer")
        )
        env_id = env.get(supervisor_lib.DECISION_ID_ENV)
        env_cause = env.get(supervisor_lib.DECISION_CAUSE_ENV)
        resume_rec = {
            "kind": "resume", "run_id": "b", "ts": 130.0, "rel_s": 10.0,
            "dp": 4, "prev_dp": 8, "resharded": True,
            "decision_id": int(env_id) if env_id else None,
            "decision_cause": env_cause,
        }
        ledger = goodput_lib.run_ledger([
            {"kind": "goodput", "run_id": "a", "ts": 100.0, "final": True,
             "productive_s": 50.0, "data_stall_s": 10.0, "elapsed_s": 60.0},
            resume_rec,
            {"kind": "goodput", "run_id": "b", "ts": 150.0, "final": True,
             "productive_s": 20.0, "elapsed_s": 20.0},
        ]) or {}

        # re-trace with the WHOLE plane up: hub snapshot live, arbiter
        # holding post-preemption state, env stamped, ledger folded
        fn2, args2 = _dp_setup(m, shard_weight_update=True)
        armed_train = str(jax.make_jaxpr(fn2)(*args2))
        armed_serve = str(jax.make_jaxpr(forward)(params, bn, x))

    out: list[Violation] = []
    rollup = snap.get("rollup") or {}
    partition_gap = abs(
        sum(
            ledger.get(f"{b}_s", 0.0) for b in goodput_lib.ALL_BUCKETS
        ) - ledger.get("elapsed_s", -1.0)
    )
    ran = (
        rollup.get("runs_aggregated") == 2  # vacuity guard: ZERO is a trip
        and rollup.get("breach_count") == 1
        and rollup.get("total_chips") == 10.0  # 8 + 2 initial allocations
        and isinstance(rollup.get("last_decision_id"), float)
        and int(rollup["last_decision_id"]) >= 1
        and federated.endswith("# EOF\n")
        and 'run="svc"' in federated
        and "tpu_dist_pod_runs_aggregated 2" in federated
        # the chain: ONE integer id across scheduler ledger, completion
        # grant, allocation file, relaunch env, resume record
        and isinstance(did, int)
        and grant.get("decision_id") == did
        and donate.get("cause") == "serve_breach"
        and alloc_meta.get("decision_id") == did
        and env_id == str(did)
        and resume_rec["decision_id"] == did
        # the attribution: the gap landed in preempt_for_serve_s and
        # the bucket partition stayed EXACT
        and ledger.get("preempt_for_serve_s") == 20.0
        and partition_gap < 1e-6
    )
    if not ran:
        out.append(
            Violation(
                "TD123",
                "<jaxpr:pod_hub_noop>",
                0,
                "the TD123 probe armed the pod telemetry plane but it "
                "did not actually run (fewer than two runs aggregated, "
                "the federated page failed to round-trip, the "
                "donate→grant pair never fired or split across two "
                "decision ids, the id failed to propagate through the "
                "allocation file / relaunch env / resume record, or the "
                "goodput partition broke) — the armed-vs-off comparison "
                "would be vacuous (tpu_dist/obs/hub.py contract)",
                snippet="pod telemetry plane probe did not fire",
            )
        )
    if base_train != armed_train:
        out.append(
            Violation(
                "TD123",
                "<jaxpr:pod_hub_noop>",
                0,
                "the traced train step CHANGED when the pod telemetry "
                "plane was armed (federated hub scrape mid-audit, "
                "hub-fed arbiter, full decision-id chain, serve-preempt "
                "goodput attribution) — the telemetry plane must stay "
                "host-side file arithmetic around the unmodified "
                "compiled step (tpu_dist/obs/hub.py contract, "
                "docs/observability.md 'Pod telemetry hub')",
                snippet="jaxpr(train, hub_off) != jaxpr(train, hub_armed)",
            )
        )
    if base_serve != armed_serve:
        out.append(
            Violation(
                "TD123",
                "<jaxpr:pod_hub_noop>",
                0,
                "the traced serving forward step CHANGED when the pod "
                "telemetry plane was armed — a serve run being scraped "
                "by the hub and preempted by a traced fleet decision "
                "must serve the SAME compiled program it warmed "
                "(tpu_dist/obs/hub.py contract, docs/observability.md "
                "'Pod telemetry hub')",
                snippet="jaxpr(serve, hub_off) != jaxpr(serve, hub_armed)",
            )
        )
    return out


def archive_gate_noop_violations(mesh=None) -> list[Violation]:
    """TD124: the longitudinal-archive cost AND vacuity contract — trace
    the data-parallel train step bare, then arm the FULL archive kit
    exactly as CI runs it: ingest a synthetic bench history (fresh
    captures plus one stale re-emission) into a tempdir archive twice
    (the second pass must append NOTHING — idempotence by fingerprint),
    require the stale copy flagged and excluded from the band, run the
    ``--inject-regression`` probe (a past-band candidate must come back
    REGRESSED, an improvement clean, an injected changepoint localized
    by blame to the exact record), and trace the step again mid-audit.
    The jaxpr must be byte-identical — the archive is host-side file
    arithmetic, and the moment someone routes ingest or a band check
    through a compiled step, this trips. A probe that misses any leg is
    itself a violation: a dead detector silently passes every real
    regression, which is the exact wound (BENCH_r03–r05 re-emissions
    read as fresh) this subsystem exists to close."""
    import json as json_lib
    import os
    import tempfile

    import jax

    from tpu_dist.comm import mesh as mesh_lib
    from tpu_dist.obs import archive as archive_lib

    m = mesh if mesh is not None else mesh_lib.data_parallel_mesh()
    fn, args = _dp_setup(m)
    base_train = str(jax.make_jaxpr(fn)(*args))

    out: list[Violation] = []
    path = "<jaxpr:archive_gate_noop>"
    with tempfile.TemporaryDirectory(prefix="td124_") as td:
        # -- arm: a synthetic bench history — 6 fresh captures around
        # 100 img/s plus one stale-stamped re-emission of the last
        bench_path = os.path.join(td, "bench.jsonl")
        recs = []
        for i in range(6):
            recs.append({
                "metric": "synthetic_train_throughput",
                "value": 100.0 + [0.4, -0.3, 0.1, -0.2, 0.3, 0.0][i],
                "unit": "images/sec",
                "capture": {
                    "host": "td124", "bench_run_id": f"run{i:02d}",
                    "mono_s": float(i),
                },
            })
        # the stale re-emission: bench's last-good fallback re-emits the
        # newest capture with its stale stamp (the BENCH_r05 shape)
        recs.append(dict(recs[-1], stale=True, note="re-emitted last good"))
        with open(bench_path, "w") as f:
            for r in recs:
                f.write(json_lib.dumps(r) + "\n")
        arch = os.path.join(td, "archive.jsonl")
        rep1 = archive_lib.ingest_paths([bench_path], arch)
        rep2 = archive_lib.ingest_paths([bench_path], arch)
        records, _counts = archive_lib.load_archive(arch)
        band = archive_lib.band_for(
            records, "synthetic_train_throughput", "value",
        )
        probe = archive_lib.inject_probe(records)

        # -- vacuity guard: every leg must have genuinely fired
        ran = (
            rep1["appended"] == 7
            and rep1["stale_appended"] == 1
            and rep2["appended"] == 0
            and rep2["deduped"] == 7
            and band is not None and band["n"] == 6
            and probe["bands_probed"] >= 1
            and not archive_lib.probe_is_dead(probe)
        )
        if not ran:
            out.append(
                Violation(
                    "TD124",
                    path,
                    0,
                    "the archive-gate probe is VACUOUS or the detector "
                    "is dead: ingest appended "
                    f"{rep1['appended']}/{rep1['stale_appended']}-stale "
                    f"then {rep2['appended']} on re-ingest (want 7/1 "
                    "then 0 — idempotence by fingerprint with the stale "
                    f"re-emission flagged), band n="
                    f"{band['n'] if band else None} (want 6, stale "
                    "excluded), inject-regression probe gate="
                    f"{probe['gate_probe']} improvements_clean="
                    f"{probe['improvements_clean']} changepoint="
                    f"{probe['changepoint_probe']} (want caught/True/"
                    "localized) — a gate that cannot catch its own "
                    "injected regression passes every real one "
                    "(tpu_dist/obs/archive.py)",
                    snippet="inject_probe(archive) came back dead",
                )
            )

    armed_train = str(jax.make_jaxpr(fn)(*args))
    if base_train != armed_train:
        out.append(
            Violation(
                "TD124",
                path,
                0,
                "the traced train step CHANGED when the longitudinal "
                "archive kit was armed (ingest + MAD-band gate + "
                "changepoint blame + injected-regression probe mid-"
                "audit) — the archive must stay host-side file "
                "arithmetic around the unmodified compiled step "
                "(tpu_dist/obs/archive.py, docs/observability.md "
                "'Longitudinal archive & trend gating')",
                snippet="jaxpr(train, archive_off) != "
                        "jaxpr(train, archive_armed)",
            )
        )
    return out


def audit_all(mesh=None, names=None) -> tuple[dict, list[Violation]]:
    """Run every (or the named) registered case. Returns
    ``(report, violations)`` where report maps case → op counts.
    Cross-case TD104 wire-ratio checks run over whichever quantized/
    reference pairs the report contains; full (unfiltered) runs also check
    the TD105 fault-injection, TD106 telemetry, TD107 device-metrics,
    TD108 profiler-trigger, TD109 live-export/alerting, TD110
    capture-auto-analyze, TD111 elastic-resume, TD112 elastic-grow,
    TD113 flight-recorder, TD114 serving-SLO, TD115 memory-ledger,
    TD122 tenancy-arbitration, TD123 pod-telemetry-hub, and TD124
    archive-gate no-op invariants."""
    report: dict = {}
    violations: list[Violation] = []
    for name in names if names is not None else registered_cases():
        counts, vs = audit_case(name, mesh)
        report[name] = counts
        violations.extend(vs)
    violations.extend(wire_ratio_violations(report))
    if names is None:
        vs = fault_noop_violations(mesh)
        report["dp_faults_noop"] = {"identical": not vs}
        violations.extend(vs)
        vs = telemetry_noop_violations(mesh)
        report["dp_telemetry_noop"] = {"identical": not vs}
        violations.extend(vs)
        vs = device_metrics_noop_violations(mesh)
        report["dp_device_metrics_noop"] = {"identical": not vs}
        violations.extend(vs)
        vs = profile_trigger_noop_violations(mesh)
        report["dp_profile_trigger_noop"] = {"identical": not vs}
        violations.extend(vs)
        vs = live_export_noop_violations(mesh)
        report["dp_live_export_noop"] = {"identical": not vs}
        violations.extend(vs)
        vs = xprof_hook_noop_violations(mesh)
        report["dp_xprof_hook_noop"] = {"identical": not vs}
        violations.extend(vs)
        vs = elastic_resume_noop_violations(mesh)
        report["dp_elastic_resume_noop"] = {"identical": not vs}
        violations.extend(vs)
        vs = elastic_grow_noop_violations(mesh)
        report["dp_elastic_grow_noop"] = {"identical": not vs}
        violations.extend(vs)
        vs = flight_recorder_noop_violations(mesh)
        report["dp_flight_recorder_noop"] = {"identical": not vs}
        violations.extend(vs)
        vs = serving_slo_noop_violations(mesh)
        report["serving_slo_noop"] = {"identical": not vs}
        violations.extend(vs)
        vs = memory_ledger_noop_violations(mesh)
        report["dp_memory_ledger_noop"] = {"identical": not vs}
        violations.extend(vs)
        vs = tenancy_arbitration_noop_violations(mesh)
        report["tenancy_arbitration_noop"] = {"identical": not vs}
        violations.extend(vs)
        vs = pod_hub_noop_violations(mesh)
        report["pod_hub_noop"] = {"identical": not vs}
        violations.extend(vs)
        vs = archive_gate_noop_violations(mesh)
        report["archive_gate_noop"] = {"identical": not vs}
        violations.extend(vs)
    return report, violations


def _compare(name: str, counts: dict, budget: CollectiveBudget) -> list[Violation]:
    out: list[Violation] = []
    path = f"<jaxpr:{name}>"
    actual = counts["collectives"]
    for prim in sorted(set(actual) | set(budget.collectives)):
        want, got = budget.collectives.get(prim, 0), actual.get(prim, 0)
        if want != got:
            out.append(
                Violation(
                    "TD101",
                    path,
                    0,
                    f"{prim}: expected {want} per step, jaxpr has {got} — "
                    "the compiled step's collective inventory drifted from "
                    "the parallelism config's budget",
                    snippet=f"{prim}:{got}",
                )
            )
    if counts["transfers"] > budget.transfers:
        out.append(
            Violation(
                "TD102",
                path,
                0,
                f"{counts['transfers']} device_put transfer op(s) inside "
                f"the compiled step (budget {budget.transfers}) — "
                "host↔device traffic on the hot path",
                snippet=f"device_put:{counts['transfers']}",
            )
        )
    if budget.bf16_to_f32 is not None and counts["bf16_to_f32"] != budget.bf16_to_f32:
        out.append(
            Violation(
                "TD103",
                path,
                0,
                f"{counts['bf16_to_f32']} bf16→f32 converts, mixed-precision "
                f"policy declares {budget.bf16_to_f32} — an op is implicitly "
                "promoting to f32",
                snippet=f"bf16_to_f32:{counts['bf16_to_f32']}",
            )
        )
    return out
