from tpu_dist.evaluation.validate import validate  # noqa: F401
