"""Distributed evaluation (reference ``utils/validation.py:7-52``).

Same contract: eval the model over the sharded test set, reduce loss /
top-1 / top-5 across replicas, display progress on rank 0, return top-1.

Deliberate fixes over the reference (documented, SURVEY §3.4 / §7):

* The reference's per-batch ``dist.barrier()`` + three ``reduce_mean`` calls
  (``validation.py:30-34``) become collectives *inside* the compiled eval
  step — no host round-trips.
* The reference averages per-batch averages over a padding
  ``DistributedSampler`` (``distributed.py:74``), double-counting the
  wrap-around examples. Here padded slots carry a 0 mask and global sums are
  divided once, so every test example counts exactly once.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

import jax

from tpu_dist.metrics.meters import AverageMeter, ProgressMeter
from tpu_dist.metrics.logging import rank0_print
from tpu_dist.obs import counters, spans


def validate(loader, state, eval_step: Callable, *, log_every: int = 50, epoch: Optional[int] = None):
    """Returns ``(top1, top5, loss)`` as floats (global, exact).

    ``loader`` must yield ``(images, labels, mask)`` batches
    (``DataLoader(with_mask=True)``); ``eval_step`` comes from
    ``make_eval_step``.
    """
    batch_time = AverageMeter("Time", ":6.3f")
    losses = AverageMeter("Loss", ":.4e")
    top1 = AverageMeter("Acc@1", ":6.2f")
    top5 = AverageMeter("Acc@5", ":6.2f")
    progress = ProgressMeter(
        len(loader), batch_time, losses, top1, top5, prefix="Test: "
    )

    tot = {"loss": 0.0, "top1": 0.0, "top5": 0.0, "count": 0.0}
    t_eval = time.perf_counter()
    end = time.time()
    for i, (images, labels, mask) in enumerate(loader):
        sums = eval_step(state, images, labels, mask)
        # ONE device→host transfer per batch (a per-key float() would
        # issue four blocking round-trips)
        sums = {k: float(v) for k, v in jax.device_get(sums).items()}
        n = max(sums["count"], 1.0)
        for k in tot:
            tot[k] += sums[k]
        losses.update(sums["loss"] / n, int(n))
        top1.update(sums["top1"] / n * 100.0, int(n))
        top5.update(sums["top5"] / n * 100.0, int(n))
        batch_time.update(time.time() - end)
        end = time.time()
        if i % log_every == 0:
            progress.display(i)

    n = max(tot["count"], 1.0)
    t1, t5, loss = tot["top1"] / n * 100.0, tot["top5"] / n * 100.0, tot["loss"] / n
    # telemetry (host-side): one span for the whole distributed eval pass
    spans.add_event(
        "eval/validate", t_eval, time.perf_counter() - t_eval, epoch=epoch
    )
    counters.inc("eval.runs")
    rank0_print(f" * Acc@1 {t1:.3f} Acc@5 {t5:.3f}" + (f" (epoch {epoch})" if epoch is not None else ""))
    return t1, t5, loss
