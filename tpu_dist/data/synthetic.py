"""Synthetic CIFAR-shaped data for tests and throughput benches.

The BASELINE metric is seconds/epoch and images/sec/chip (SURVEY §6) — a
throughput measurement that random pixels exercise identically to real ones.
Deterministic per seed.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


def synthetic_cifar(
    n: int = 50_000,
    num_classes: int = 100,
    image_size: int = 32,
    seed: int = 0,
) -> Tuple[np.ndarray, np.ndarray]:
    rng = np.random.default_rng(seed)
    images = rng.integers(0, 256, size=(n, image_size, image_size, 3), dtype=np.uint8)
    labels = rng.integers(0, num_classes, size=(n,), dtype=np.int32)
    return images, labels


def synthetic_imagenet(
    n: int = 10_000,
    num_classes: int = 1000,
    image_size: int = 224,
    seed: int = 0,
) -> Tuple[np.ndarray, np.ndarray]:
    """ImageNet-shaped random data (BASELINE's ResNet-50 / ViT-B configs)."""
    return synthetic_cifar(n, num_classes, image_size, seed)
