"""Synthetic CIFAR-shaped data for tests and throughput benches.

The BASELINE metric is seconds/epoch and images/sec/chip (SURVEY §6) — a
throughput measurement that random pixels exercise identically to real ones.
Deterministic per seed.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


def synthetic_cifar(
    n: int = 50_000,
    num_classes: int = 100,
    image_size: int = 32,
    seed: int = 0,
) -> Tuple[np.ndarray, np.ndarray]:
    rng = np.random.default_rng(seed)
    images = rng.integers(0, 256, size=(n, image_size, image_size, 3), dtype=np.uint8)
    labels = rng.integers(0, num_classes, size=(n,), dtype=np.int32)
    return images, labels


def synthetic_imagenet(
    n: int = 10_000,
    num_classes: int = 1000,
    image_size: int = 224,
    seed: int = 0,
) -> Tuple[np.ndarray, np.ndarray]:
    """ImageNet-shaped random data (BASELINE's ResNet-50 / ViT-B configs)."""
    return synthetic_cifar(n, num_classes, image_size, seed)


def synthetic_quadrant(
    n: int = 10_000,
    image_size: int = 32,
    seed: int = 0,
) -> Tuple[np.ndarray, np.ndarray]:
    """LEARNABLE synthetic task: 4 classes, label = the image quadrant
    holding a bright blob on a noisy background. Unlike random labels this
    is generalizable, so end-to-end runs can assert real convergence
    (val accuracy ≫ 25% chance) without any external dataset.
    """
    rng = np.random.default_rng(seed)
    h = image_size
    images = rng.integers(40, 120, size=(n, h, h, 3)).astype(np.int32)
    labels = rng.integers(0, 4, size=(n,)).astype(np.int32)
    half = h // 2
    for quad in range(4):
        idx = np.where(labels == quad)[0]
        r, c = divmod(quad, 2)
        images[idx, r * half : (r + 1) * half, c * half : (c + 1) * half, :] += 100
    return np.clip(images, 0, 255).astype(np.uint8), labels
