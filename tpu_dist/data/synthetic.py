"""Synthetic CIFAR-shaped data for tests and throughput benches.

The BASELINE metric is seconds/epoch and images/sec/chip (SURVEY §6) — a
throughput measurement that random pixels exercise identically to real ones.
Deterministic per seed.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


def synthetic_cifar(
    n: int = 50_000,
    num_classes: int = 100,
    image_size: int = 32,
    seed: int = 0,
) -> Tuple[np.ndarray, np.ndarray]:
    rng = np.random.default_rng(seed)
    images = rng.integers(0, 256, size=(n, image_size, image_size, 3), dtype=np.uint8)
    labels = rng.integers(0, num_classes, size=(n,), dtype=np.int32)
    return images, labels


def synthetic_imagenet(
    n: int = 10_000,
    num_classes: int = 1000,
    image_size: int = 224,
    seed: int = 0,
) -> Tuple[np.ndarray, np.ndarray]:
    """ImageNet-shaped random data (BASELINE's ResNet-50 / ViT-B configs)."""
    return synthetic_cifar(n, num_classes, image_size, seed)


def synthetic_multifactor(
    n: int = 10_000,
    image_size: int = 32,
    seed: int = 0,
    label_noise: float = 0.2,
    amp: float = 0.18,
) -> Tuple[np.ndarray, np.ndarray]:
    """DISCRIMINATING convergence task (VERDICT r2 #4): 16 classes from two
    independent factors, plus label noise — built so a run can't memorize
    it in one epoch and flatline (the failure mode of the quadrant task).

    * factor 1 (position): a faint +``amp``·σ blob in one of 4 quadrants;
    * factor 2 (texture): a faint sinusoidal stripe pattern — one of 2
      orientations × 2 spatial frequencies — the conv stack must learn
      oriented frequency filters, not just mean pooling;
    * class = 4·f1 + f2 (chance = 6.25%);
    * ``label_noise`` of the TRAIN labels are resampled uniformly, so
      (a) 100% train accuracy is impossible without gross overfitting and
      (b) optimization dynamics matter: a constant high LR keeps bouncing
      off the noise floor, while the reference's MultiStepLR decay
      (distributed.py:64 semantics) settles — the convergence test asserts
      this gap, making the LR schedule *visibly* load-bearing.

    Signals sit at ``amp`` (default 0.18) of the background σ ≈ 32 grey
    levels, i.e. ~6 levels — learnable, but only over many epochs.
    Evaluation splits should pass ``label_noise=0`` so val accuracy
    measures the true function. Tuned operating point (20 epochs,
    batch 256, n=4096, lr 0.8, tiny-resnet): MultiStepLR(10,15)×0.1
    reaches ~98.9% val top-1 while constant LR bounces at ~93.7% — a
    >5-point schedule gap, the discriminating property
    ``tests/test_convergence.py::test_multifactor_convergence_and_schedule_matters``
    asserts.
    """
    rng = np.random.default_rng(seed)
    h = image_size
    half = h // 2
    x = rng.normal(0.0, 1.0, size=(n, h, h, 3)).astype(np.float32)
    f1 = rng.integers(0, 4, n)
    f2 = rng.integers(0, 4, n)
    for quad in range(4):
        idx = np.where(f1 == quad)[0]
        r, c = divmod(quad, 2)
        x[idx, r * half : (r + 1) * half, c * half : (c + 1) * half, :] += amp
    yy, xx = np.meshgrid(np.arange(h), np.arange(h), indexing="ij")
    stripes = [
        np.sin(2 * np.pi * 2 * xx / h),
        np.sin(2 * np.pi * 2 * yy / h),
        np.sin(2 * np.pi * 5 * xx / h),
        np.sin(2 * np.pi * 5 * yy / h),
    ]
    for v in range(4):
        idx = np.where(f2 == v)[0]
        x[idx] += amp * stripes[v][None, :, :, None].astype(np.float32)
    labels = (4 * f1 + f2).astype(np.int32)
    if label_noise > 0:
        flip = rng.random(n) < label_noise
        labels[flip] = rng.integers(0, 16, int(flip.sum())).astype(np.int32)
    images = np.clip(128.0 + 32.0 * x, 0, 255).astype(np.uint8)
    return images, labels


def synthetic_quadrant(
    n: int = 10_000,
    image_size: int = 32,
    seed: int = 0,
) -> Tuple[np.ndarray, np.ndarray]:
    """LEARNABLE synthetic task: 4 classes, label = the image quadrant
    holding a bright blob on a noisy background. Unlike random labels this
    is generalizable, so end-to-end runs can assert real convergence
    (val accuracy ≫ 25% chance) without any external dataset.
    """
    rng = np.random.default_rng(seed)
    h = image_size
    images = rng.integers(40, 120, size=(n, h, h, 3)).astype(np.int32)
    labels = rng.integers(0, 4, size=(n,)).astype(np.int32)
    half = h // 2
    for quad in range(4):
        idx = np.where(labels == quad)[0]
        r, c = divmod(quad, 2)
        images[idx, r * half : (r + 1) * half, c * half : (c + 1) * half, :] += 100
    return np.clip(images, 0, 255).astype(np.uint8), labels
