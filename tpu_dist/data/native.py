"""ctypes bridge to the native C++ input pipeline (``tpu_dist/csrc``).

The reference leans on native code for its input path (torchvision's C
extensions + DataLoader worker processes, SURVEY §2.2 N7); this module is
the TPU build's equivalent: a fused gather+pad+crop+normalize over the
batch in multi-threaded C++. Falls back to the numpy implementation in
``tpu_dist.data.transforms`` when the shared library isn't built.

Build once with ``make -C tpu_dist/csrc`` — or let :func:`ensure_built`
compile it on first use (cached; failures degrade to numpy silently but
are reported by :func:`available`).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

from tpu_dist.data import transforms

_CSRC = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "csrc")
_SO = os.path.join(_CSRC, "build", "libtpu_dist_pipeline.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SO):
            try:  # build on first use; tolerate missing toolchain
                subprocess.run(
                    ["make", "-C", _CSRC],
                    check=True,
                    capture_output=True,
                    timeout=120,
                )
            except Exception:
                return None
        try:
            lib = ctypes.CDLL(_SO)
            lib.tpu_dist_augment_batch.restype = ctypes.c_int
            lib.tpu_dist_augment_batch.argtypes = [
                ctypes.POINTER(ctypes.c_uint8),   # images
                ctypes.POINTER(ctypes.c_int64),   # indices
                ctypes.POINTER(ctypes.c_float),   # out
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
                ctypes.c_int64,                   # pad
                ctypes.c_uint64,                  # seed
                ctypes.POINTER(ctypes.c_float),   # mean
                ctypes.POINTER(ctypes.c_float),   # std
                ctypes.c_int,                     # train
                ctypes.c_int,                     # n_threads
            ]
            if lib.tpu_dist_pipeline_abi_version() != 1:
                return None
            _lib = lib
        except (OSError, AttributeError):
            # AttributeError: a stale/foreign .so missing our symbols — the
            # promised silent numpy fallback must cover that case too.
            return None
        return _lib


def available() -> bool:
    return _load() is not None


def gather_augment(
    images: np.ndarray,
    indices: np.ndarray,
    *,
    seed: int,
    train: bool,
    padding: int = 4,
    mean: np.ndarray = transforms.CIFAR100_MEAN,
    std: np.ndarray = transforms.CIFAR100_STD,
    n_threads: int = 0,
) -> np.ndarray:
    """Fused ``normalize(random_crop(images[indices]))`` → f32 NHWC batch.

    Uses the C++ pipeline when built; otherwise the numpy reference path
    (identical semantics, different crop-offset RNG stream).
    """
    lib = _load()
    n = len(indices)
    _, h, w, c = images.shape
    if lib is not None:
        images = np.ascontiguousarray(images)
        idx = np.ascontiguousarray(indices, np.int64)
        out = np.empty((n, h, w, c), np.float32)
        mean32 = np.ascontiguousarray(mean, np.float32)
        std32 = np.ascontiguousarray(std, np.float32)
        rc = lib.tpu_dist_augment_batch(
            images.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            n, h, w, c,
            padding if train else 0,
            np.uint64(seed & 0xFFFFFFFFFFFFFFFF),
            mean32.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            std32.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            1 if train else 0,
            n_threads,
        )
        if rc == 0:
            return out
    # numpy fallback
    batch = images[indices]
    if train:
        rng = np.random.default_rng(seed)
        batch = transforms.random_crop_batch(batch, rng, padding)
    return (batch.astype(np.float32) / 255.0 - mean) / std
