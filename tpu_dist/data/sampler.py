"""Distributed sampler with exact reference semantics, torch-free.

Re-implements the contract of ``torch.utils.data.distributed.DistributedSampler``
as the reference uses it (``distributed.py:70,74,81``):

* same epoch-seeded global permutation on every shard (``set_epoch``, whose
  shuffle-correctness role is explained in reference ``tutorials/2:§2``),
* pad-to-even division across shards (and, new here, the pad indices are
  *reported* so evaluation can mask them instead of double-counting —
  the reference's eval bug documented in SURVEY §3.4),
* optional ``drop_last`` (the grad-accum trainer's loader,
  ``distributed_gradient_accumulation.py:71``).

On TPU one process drives many chips, so "shard" here means *host process*;
the per-host batch is split further across local devices by the sharding of
the batch array, not by the sampler.
"""

from __future__ import annotations

import numpy as np


class DistributedSampler:
    def __init__(
        self,
        num_examples: int,
        num_shards: int = 1,
        shard_id: int = 0,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = False,
    ):
        if not (0 <= shard_id < num_shards):
            raise ValueError(f"shard_id {shard_id} out of range for {num_shards} shards")
        self.num_examples = num_examples
        self.num_shards = num_shards
        self.shard_id = shard_id
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0
        self.offset = 0  # consumed-prefix skip (elastic mid-epoch resume)
        self._recompute_sizes()

    def _recompute_sizes(self) -> None:
        remaining = self.num_examples - self.offset
        if self.drop_last:
            self.num_samples = remaining // self.num_shards
        else:
            self.num_samples = -(-remaining // self.num_shards)  # ceil
        self.total_size = self.num_samples * self.num_shards

    def set_epoch(self, epoch: int) -> None:
        """Reference ``train_sampler.set_epoch(epoch)`` (``distributed.py:81``).
        Also clears any mid-epoch offset — the skip applies to the resumed
        epoch only; the next epoch partitions the full permutation again."""
        self.epoch = epoch
        if self.offset:
            self.set_offset(0)

    def set_offset(self, n_examples: int) -> None:
        """Skip the first ``n_examples`` of the current epoch's GLOBAL
        order and re-partition the remainder over the shards — the elastic
        mid-epoch-resume entry point (docs/resilience.md).

        Why this is exact: shards advance in lockstep (steps are
        synchronous), so after ``k`` global batches every shard has
        consumed the first ``k * local_batch`` elements of its strided
        stream — and the union of those per-shard prefixes is precisely
        the first ``k * global_batch`` elements of the epoch permutation.
        Resuming with ``offset = k * global_batch`` therefore hands out
        exactly the not-yet-seen examples, no matter how many shards the
        OLD run had: nothing is dropped, nothing is double-seen. (For the
        same shard count, ``order[C:][j::n] == order[j::n][C//n:]`` since
        the global batch divides over the shards — the offset path
        strictly generalizes ``DataLoader.iter_from``.)"""
        if not 0 <= n_examples <= self.num_examples:
            raise ValueError(
                f"offset {n_examples} outside [0, {self.num_examples}]"
            )
        self.offset = int(n_examples)
        self._recompute_sizes()

    def indices(self) -> np.ndarray:
        """This shard's indices for the current epoch (deterministic)."""
        if self.shuffle:
            g = np.random.default_rng(self.seed + self.epoch)
            order = g.permutation(self.num_examples)
        else:
            order = np.arange(self.num_examples)
        if self.offset:
            order = order[self.offset :]
        if self.drop_last:
            order = order[: self.total_size]
        elif 0 < len(order) < self.total_size:
            # wrap-around padding, same policy as torch's sampler; tile so
            # even num_shards > num_examples pads fully
            reps = -(-self.total_size // len(order))
            order = np.tile(order, reps)[: self.total_size]
        return order[self.shard_id :: self.num_shards]

    def pad_mask(self) -> np.ndarray:
        """True for real examples, False for wrap-around padding — lets eval
        count each example exactly once (deliberate fix of SURVEY §3.4)."""
        if self.drop_last:
            return np.ones(self.num_samples, dtype=bool)
        # Padding occupies the tail of the padded global order regardless of
        # shuffle (the permutation covers only the first num_examples slots
        # past the consumed offset).
        positions = np.arange(self.shard_id, self.total_size, self.num_shards)
        return positions < self.num_examples - self.offset

    def __len__(self) -> int:
        return self.num_samples
