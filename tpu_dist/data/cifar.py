"""Self-contained CIFAR-100 reader (torchvision replacement, SURVEY §2.2 N7).

Reads the standard ``cifar-100-python`` pickle layout that the reference's
``datasets.CIFAR100(root='./data', download=True)`` produces
(``utils/dataset.py:10-13``). This build runs with zero network egress, so
there is no downloader: the loader looks for an existing extraction (or
``.tar.gz``) under ``data_dir`` and raises a clear error otherwise; tests
and benches fall back to :func:`tpu_dist.data.synthetic.synthetic_cifar`.
"""

from __future__ import annotations

import os
import pickle
import tarfile
from typing import Tuple

import numpy as np

def _find_root(data_dir: str, dirname: str, archive: str, label: str) -> str:
    """Locate an extracted dataset dir, extracting the archive if present."""
    d = os.path.join(data_dir, dirname)
    if os.path.isdir(d):
        return d
    tar = os.path.join(data_dir, archive)
    if os.path.isfile(tar):
        with tarfile.open(tar, "r:gz") as tf:
            tf.extractall(data_dir)
        if os.path.isdir(d):
            return d
    raise FileNotFoundError(
        f"{label} not found under {data_dir!r} (need {dirname}/ or {archive}); "
        "this environment has no network egress — place the archive there, or use "
        "dataset='synthetic'."
    )


def load_cifar100(data_dir: str = "./data", train: bool = True) -> Tuple[np.ndarray, np.ndarray]:
    """Returns ``(images_u8 [N,32,32,3], labels_i32 [N])`` — fine labels,
    matching the reference's ``datasets.CIFAR100`` splits."""
    root = _find_root(data_dir, "cifar-100-python", "cifar-100-python.tar.gz", "CIFAR-100")
    fname = "train" if train else "test"
    with open(os.path.join(root, fname), "rb") as f:
        d = pickle.load(f, encoding="latin1")
    data = np.asarray(d["data"], np.uint8).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    labels = np.asarray(d["fine_labels"], np.int32)
    return np.ascontiguousarray(data), labels


def load_cifar10(data_dir: str = "./data", train: bool = True) -> Tuple[np.ndarray, np.ndarray]:
    """CIFAR-10 in the standard ``cifar-10-batches-py`` layout
    (``data_batch_1..5`` / ``test_batch`` pickles). Same NHWC uint8 output
    contract as :func:`load_cifar100`."""
    root = _find_root(data_dir, "cifar-10-batches-py", "cifar-10-python.tar.gz", "CIFAR-10")
    names = [f"data_batch_{i}" for i in range(1, 6)] if train else ["test_batch"]
    datas, labels = [], []
    for n in names:
        with open(os.path.join(root, n), "rb") as f:
            d = pickle.load(f, encoding="latin1")
        datas.append(np.asarray(d["data"], np.uint8))
        labels.append(np.asarray(d["labels"], np.int32))
    data = np.concatenate(datas).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    return np.ascontiguousarray(data), np.concatenate(labels)
