"""NumPy image transforms (torchvision replacement, SURVEY §2.2 N7).

Mirrors the reference pipeline exactly (``utils/dataset.py:5-21``):
train = RandomCrop(32, padding=4) + normalize; test = normalize only; same
hard-coded CIFAR-100 per-channel mean/std. Operates on NHWC uint8 batches
and is fully vectorized — per-batch host cost is a copy + gather, the rest
(normalize) is folded into the device step where XLA fuses it.
"""

from __future__ import annotations

import numpy as np

# utils/dataset.py:8,20
CIFAR100_MEAN = np.array([0.5070751592371323, 0.48654887331495095, 0.4409178433670343], np.float32)
CIFAR100_STD = np.array([0.2673342858792401, 0.2564384629170883, 0.27615047132568404], np.float32)
# standard torchvision CIFAR-10 statistics
CIFAR10_MEAN = np.array([0.4914, 0.4822, 0.4465], np.float32)
CIFAR10_STD = np.array([0.2470, 0.2435, 0.2616], np.float32)


def normalize(x: np.ndarray) -> np.ndarray:
    """uint8 NHWC → float32 normalized (ToTensor + Normalize)."""
    return (x.astype(np.float32) / 255.0 - CIFAR100_MEAN) / CIFAR100_STD


def random_crop_batch(x: np.ndarray, rng: np.random.Generator, padding: int = 4) -> np.ndarray:
    """Vectorized RandomCrop(H, padding=4) over a NHWC batch.

    Pads with zeros (torch default) and gathers one HxW window per image via
    strided view indexing — no Python loop over the batch.
    """
    n, h, w, c = x.shape
    xp = np.pad(x, ((0, 0), (padding, padding), (padding, padding), (0, 0)))
    ys = rng.integers(0, 2 * padding + 1, size=n)
    xs = rng.integers(0, 2 * padding + 1, size=n)
    # windowed view: [N, 2p+1, 2p+1, H, W, C] is too big; gather row/col idx
    rows = ys[:, None] + np.arange(h)[None, :]          # [N, H]
    cols = xs[:, None] + np.arange(w)[None, :]          # [N, W]
    out = xp[np.arange(n)[:, None, None], rows[:, :, None], cols[:, None, :], :]
    return out


def train_augment(x: np.ndarray, rng: np.random.Generator) -> np.ndarray:
    """Reference train transform (crop only — the reference uses no flip,
    ``utils/dataset.py:5-9``), returning float32 normalized NHWC."""
    return normalize(random_crop_batch(x, rng))


def eval_transform(x: np.ndarray) -> np.ndarray:
    return normalize(x)
