"""Batched, prefetching device feeder — the ``DataLoader``/``ParallelLoader``
role (reference ``distributed.py:71,75``: ``DataLoader(..., num_workers=4,
pin_memory=True, sampler=...)``; torch-xla's ``ParallelLoader`` in the
BASELINE north star).

Differences from torch, by design:

* Datasets at this framework's scope are in-memory numpy arrays, so there
  are no worker *processes*; a single background thread pipelines host-side
  augmentation + H2D placement one batch ahead of the device (the role of
  ``pin_memory`` + workers). When the optional C++ pipeline extension is
  built (``tpu_dist/csrc``), augmentation runs there in native threads.
* The loader emits **globally sharded** ``jax.Array`` batches: one process
  feeds all its local chips (SURVEY §7 design stance), the leading batch
  dim is laid over the mesh's ``data`` axis.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Iterator, Optional, Tuple

import numpy as np
from jax.sharding import Mesh

from tpu_dist.comm import mesh as mesh_lib
from tpu_dist.data.sampler import DistributedSampler
from tpu_dist.obs import counters, spans
from tpu_dist.resilience import faults


class LoaderProducerDiedError(RuntimeError):
    """The prefetch producer thread died without finishing the epoch (and
    without surfacing an exception) — e.g. killed at interpreter teardown.
    Raised by the consumer watchdog instead of blocking on ``q.get()``
    forever (docs/resilience.md)."""


class DataLoader:
    def __init__(
        self,
        images: np.ndarray,
        labels: np.ndarray,
        batch_size: int,
        sampler: DistributedSampler,
        mesh: Mesh,
        transform: Optional[Callable[[np.ndarray, np.random.Generator], np.ndarray]] = None,
        eval_transform: Optional[Callable[[np.ndarray], np.ndarray]] = None,
        gather_transform: Optional[Callable] = None,
        seed: int = 0,
        prefetch: int = 2,
        with_mask: bool = False,
        batch_divisor: Optional[int] = None,
        shard_axes=mesh_lib.DATA_AXIS,
        watchdog_timeout: float = 5.0,
    ):
        """``batch_size`` is the PER-PROCESS batch (the reference's manual
        ``global_batch / nprocs`` split, ``distributed.py:67``, happens in
        the trainer). ``with_mask`` adds the sampler's pad mask to each batch
        for exact distributed eval.

        ``gather_transform(images, sel, seed=...)`` is the fused fast path
        (gather + augment + normalize in one pass — the native C++ pipeline,
        ``tpu_dist.data.native.gather_augment``); when given it replaces
        ``transform``/``eval_transform``.

        ``watchdog_timeout`` is the consumer's poll period (seconds) for
        noticing a DEAD producer thread: a slow producer just keeps the
        consumer polling, but a producer that died without its end-of-epoch
        sentinel raises :class:`LoaderProducerDiedError` within one tick
        instead of hanging the epoch forever."""
        n_local = batch_divisor or mesh_lib.local_device_count()
        if batch_size % n_local:
            raise ValueError(
                f"per-process batch {batch_size} must divide over {n_local} "
                f"(local data-parallel) devices"
            )
        self.images = images
        self.labels = labels
        self.batch_size = batch_size
        self.sampler = sampler
        self.mesh = mesh
        self.transform = transform
        self.eval_transform = eval_transform
        self.gather_transform = gather_transform
        self.seed = seed
        self.prefetch = max(1, prefetch)
        self.with_mask = with_mask
        self.shard_axes = shard_axes
        self.watchdog_timeout = watchdog_timeout

    def __len__(self) -> int:
        return len(self.sampler) // self.batch_size if self.sampler.drop_last else -(
            -len(self.sampler) // self.batch_size
        )

    def _host_batches(self, start_batch: int = 0) -> Iterator[Tuple[np.ndarray, ...]]:
        idx = self.sampler.indices()
        mask = self.sampler.pad_mask() if self.with_mask else None
        n = len(idx)
        nb = len(self)
        for b in range(start_batch, nb):
            # Epoch-, rank- AND batch-keyed augmentation stream (init_seeds
            # parity, reference distributed_mp.py:29-39,56).  Keying by the
            # batch index makes batch b's augmentation independent of whether
            # batches 0..b-1 were produced in this process — the property
            # exact mid-epoch resume relies on (resume at step k replays the
            # identical remaining stream).
            rng = np.random.default_rng(
                (self.seed, self.sampler.epoch, self.sampler.shard_id, b)
            )
            sel = idx[b * self.batch_size : (b + 1) * self.batch_size]
            pad = self.batch_size - len(sel)
            bmask = mask[b * self.batch_size : b * self.batch_size + len(sel)] if self.with_mask else None
            if pad:
                # Last partial batch: pad to a static shape with WRAP-AROUND
                # samples from the start of this shard's epoch stream — the
                # same semantics as torch's DistributedSampler padding
                # (distinct examples seen twice, not one example repeated,
                # so the extra gradient weight is spread like torch's).
                # Eval (with_mask=True) masks the tail out exactly either way.
                sel = np.concatenate([sel, np.resize(idx, pad)])
                if bmask is not None:
                    bmask = np.concatenate([bmask, np.zeros(pad, bool)])
            if self.gather_transform is not None:
                imgs = self.gather_transform(
                    self.images, sel, seed=int(rng.integers(0, 2**63))
                )
            else:
                imgs = self.images[sel]
                if self.transform is not None:
                    imgs = self.transform(imgs, rng)
                elif self.eval_transform is not None:
                    imgs = self.eval_transform(imgs)
            out = (imgs, self.labels[sel])
            if self.with_mask:
                out = out + (bmask.astype(np.float32),)
            yield out

    def __iter__(self):
        """Yields device-sharded batches, pipelined one step ahead."""
        return self.iter_from(0)

    def iter_from(self, start_batch: int):
        """Iterate from batch ``start_batch`` of the current epoch — the
        exact-mid-epoch-resume entry point.  Skipped batches are never
        gathered or augmented (index slicing, not produce-and-discard), and
        the per-batch RNG keying in ``_host_batches`` guarantees batch b is
        bit-identical to what an uninterrupted epoch would have produced."""
        q: "queue.Queue" = queue.Queue(maxsize=self.prefetch)
        err = []
        stop = threading.Event()
        killed = []  # --fault_plan loader_stall: producer died, no sentinel

        def producer():
            try:
                for b, hb in enumerate(
                    self._host_batches(start_batch), start=start_batch
                ):
                    if faults.on_loader_batch(b, self.sampler.epoch) == "die":
                        # simulate a producer killed mid-epoch: exit WITHOUT
                        # the end-of-epoch sentinel (the consumer watchdog
                        # below must notice, not hang)
                        killed.append(b)
                        return
                    # telemetry: the producer THREAD writes the registry —
                    # counters are locked for exactly this
                    with spans.span("loader/produce", batch=b):
                        batch = mesh_lib.shard_batch(self.mesh, hb, self.shard_axes)
                    counters.inc("loader.batches_produced")
                    # bounded put that notices consumer abandonment (e.g. the
                    # trainer's steps_per_epoch early break) instead of
                    # blocking forever and leaking the thread + device batches
                    t_put = time.perf_counter()
                    while not stop.is_set():
                        try:
                            q.put(batch, timeout=0.1)
                            break
                        except queue.Full:
                            continue
                    # time the producer spent blocked on a FULL queue: the
                    # loader outrunning the device (the healthy direction)
                    counters.add_seconds(
                        "loader.producer_wait_s", time.perf_counter() - t_put
                    )
                    if stop.is_set():
                        return
            except Exception as e:  # surfaced on the consumer side
                err.append(e)
            finally:
                if not stop.is_set() and not killed:
                    q.put(None)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                t_wait = time.perf_counter()
                try:
                    item = q.get(timeout=self.watchdog_timeout)
                except queue.Empty:
                    # polling ticks count as consumer wait too — a slow
                    # producer is exactly what this counter measures
                    counters.add_seconds(
                        "loader.data_wait_s", time.perf_counter() - t_wait
                    )
                    # watchdog: only a DEAD producer with a drained queue is
                    # a failure — nothing can arrive anymore (a live-but-slow
                    # producer just keeps us polling)
                    if not t.is_alive() and q.empty():
                        if err:
                            raise err[0]
                        raise LoaderProducerDiedError(
                            "DataLoader producer thread died without "
                            "finishing the epoch (no sentinel, no error) — "
                            "likely killed mid-epoch; restart the epoch "
                            "instead of waiting on q.get() forever"
                        )
                    continue
                counters.add_seconds(
                    "loader.data_wait_s", time.perf_counter() - t_wait
                )
                if item is None:
                    break
                counters.inc("loader.batches_consumed")
                yield item
        finally:
            stop.set()
            # Abandonment teardown without busy-spinning: ONE drain makes
            # room for any put already in flight; the producer's bounded
            # put (0.1 s timeout + stop check) then either lands it in the
            # freed slot or notices the event — both exit its loop within
            # one timeout tick, so a plain join suffices. (A producer that
            # fills the freed slot re-checks `stop` right after the put and
            # returns — the queue can never refill faster than it exits.)
            while True:
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
            t.join()
            if err:
                raise err[0]
