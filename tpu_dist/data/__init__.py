from tpu_dist.data.sampler import DistributedSampler  # noqa: F401
from tpu_dist.data.loader import DataLoader  # noqa: F401
from tpu_dist.data.cifar import load_cifar10, load_cifar100  # noqa: F401
from tpu_dist.data.synthetic import synthetic_cifar  # noqa: F401
from tpu_dist.data import transforms as transforms  # noqa: F401
