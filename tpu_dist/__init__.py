"""tpu_dist — a TPU-native distributed training framework.

A brand-new JAX/XLA framework with the capabilities of the PyTorch DDP
tutorial repo ``rentainhe/pytorch-distributed-training`` (see SURVEY.md):
data-parallel training over a device mesh, gradient accumulation with
``no_sync`` semantics, bf16 mixed precision (replacing apex AMP),
cross-replica synchronized BatchNorm, sharded data loading with
epoch-seeded shuffling, cross-replica metric reduction, rank-0 logging,
distributed evaluation and checkpoint/resume.

On TPU the reference's DP and DDP engines collapse into one model: a single
process per host drives all local chips; parameters live replicated on a
``jax.sharding.Mesh`` and gradients are ``pmean``-ed over the ``data`` axis
inside one compiled step (reference: ``distributed.py:60``,
``dataparallel.py:47``).
"""

__version__ = "0.1.0"

from tpu_dist.comm import mesh as mesh  # noqa: F401


def __getattr__(name):
    # lazy top-level conveniences (avoid importing jax-heavy modules on
    # plain `import tpu_dist`)
    if name == "Trainer":
        from tpu_dist.train.trainer import Trainer

        return Trainer
    if name == "TrainConfig":
        from tpu_dist.config import TrainConfig

        return TrainConfig
    if name == "register_model":
        from tpu_dist.train.trainer import register_model

        return register_model
    raise AttributeError(f"module 'tpu_dist' has no attribute {name!r}")
