"""Unified training CLI (SURVEY §1 L4 replacement).

The reference ships six near-identical scripts × three launch modes
(``torch.distributed.launch``, ``mp.spawn``, in-process); on TPU one
process drives all local chips, so there is ONE entry point and the
reference scripts become flag presets (see the sibling modules named after
them). All reference flags are accepted (``distributed.py:18-25``).

Usage::

    python -m tpu_dist.cli.train --batch_size 256 --epochs 200 --lr 0.1
    python -m tpu_dist.cli.train --bf16 --grad_accu_steps 4
    # multi-host (one invocation per host):
    python -m tpu_dist.cli.train --num_processes 4 --process_id $RANK \
        --ip <coordinator> --port 23456
"""

from __future__ import annotations

import argparse
from typing import Optional, Sequence

from tpu_dist.config import add_reference_flags, config_from_args
from tpu_dist.metrics.logging import rank0_print


def main(argv: Optional[Sequence[str]] = None, **preset) -> None:
    parser = argparse.ArgumentParser(
        description="tpu_dist trainer (TPU-native DDP-equivalent)"
    )
    add_reference_flags(parser)
    args = parser.parse_args(argv)
    cfg = config_from_args(args, **preset)

    from tpu_dist.resilience.preemption import (  # noqa: PLC0415
        PREEMPTION_EXIT_CODE,
        PreemptedError,
    )
    from tpu_dist.train.trainer import Trainer  # lazy: jax init after parse

    trainer = Trainer(cfg)
    cfg = trainer.cfg  # --auto_shard apply may have rewritten the config
    rank0_print(
        f"tpu_dist: model={cfg.model} devices={trainer.n_devices} "
        f"global_batch={cfg.batch_size} bf16={cfg.bf16} sync_bn={cfg.sync_bn} "
        f"grad_accu_steps={cfg.grad_accu_steps}"
    )
    plan = getattr(trainer, "_plan", None)
    if plan is not None:
        pred = plan.get("predicted_step_s")
        rank0_print(
            f"tpu_dist: auto_shard={plan['mode']} plan={plan['family']}"
            + (" (applied)" if plan.get("applied") else " (advisory)")
            + (f" predicted_step={pred:g}s" if pred is not None else "")
            + f" [rates: {plan.get('gauge_source')}]"
        )
    try:
        trainer.fit()
    except PreemptedError as e:
        # graceful preemption: the emergency snapshot discipline already ran
        # inside fit(); exit with the distinct requeue-me code instead of
        # dying on the signal (launch.py propagates it)
        rank0_print(f"=> preempted: {e}; exiting {PREEMPTION_EXIT_CODE}")
        raise SystemExit(PREEMPTION_EXIT_CODE) from None


if __name__ == "__main__":
    main()
