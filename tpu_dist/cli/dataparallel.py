"""DP preset (reference ``dataparallel.py``: single-process multi-device via
``nn.DataParallel``, ``dataparallel.py:47``).

On TPU the single-controller model IS the native mode — one process drives
all local chips — so this is the plain trainer. ``--gpu`` is accepted for
command-line parity and ignored (device selection is the TPU slice).
"""

from tpu_dist.cli.train import main as _main


def main(argv=None):
    _main(argv)


if __name__ == "__main__":
    main()
