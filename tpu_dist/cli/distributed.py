"""DDP preset (reference ``distributed.py``, launched via
``torch.distributed.launch``). ``--local_rank`` is accepted for parity and
ignored — on TPU, process↔chip mapping comes from slice discovery
(``jax.distributed.initialize``), not an injected flag (SURVEY §3.5)."""

from tpu_dist.cli.train import main as _main


def main(argv=None):
    _main(argv)


if __name__ == "__main__":
    main()
