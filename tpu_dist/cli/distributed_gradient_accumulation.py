"""Gradient-accumulation preset (reference
``distributed_gradient_accumulation.py``): per-rank batch split into
``--grad_accu_steps`` sub-batches (``:77,90-98``), allreduce suppressed on
non-boundary sub-steps (``no_sync``, ``:106``), loss scaled 1/K
(``:103,110``), one optimizer step per outer step (``:118``),
``drop_last=True`` loader (``:71``). Defaults ``--grad_accu_steps 4`` (the
reference flag at ``:26`` defaults to 1, i.e. no accumulation; this preset
exists to exercise accumulation, so it picks 4)."""

from tpu_dist.cli.train import main as _main


def main(argv=None):
    import sys

    argv = list(sys.argv[1:] if argv is None else argv)
    if not any(a.startswith("--grad_accu_steps") for a in argv):
        argv += ["--grad_accu_steps", "4"]
    _main(argv, drop_last=True)


if __name__ == "__main__":
    main()
