"""DDP + apex preset (reference ``distributed_apex.py``: apex AMP ``:86``,
apex fused SyncBN ``:85``). bf16 compute + the pmean-based SyncBN (on by
default) are the TPU equivalents; seeding matches ``init_seeds`` (``:40-50``)."""

from tpu_dist.cli.train import main as _main


def main(argv=None):
    import sys

    argv = list(sys.argv[1:] if argv is None else argv)
    if not any(a.startswith("--seed") for a in argv):
        argv += ["--seed", "1"]
    _main(argv, bf16=True)


if __name__ == "__main__":
    main()
