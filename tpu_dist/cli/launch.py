"""Local multi-process launcher — ``torch.distributed.launch`` / ``torchrun``
equivalent (SURVEY §2.2 N8).

On real TPU pods you normally run ONE process per host and the TPU runtime
does slice discovery, so this launcher exists for two cases the reference's
launchers cover:

* spinning up a multi-process run on one machine (CPU emulation of
  multi-host — each process gets its own device set via
  ``--xla_force_host_platform_device_count``),
* explicitly-coordinated multi-host setups where you want rank/env control
  (`--node_rank`-style splits).

Usage::

    python -m tpu_dist.cli.launch --nproc 2 --devices_per_proc 4 -- \
        python -m tpu_dist.cli.train --dataset synthetic --epochs 1

Injects ``--num_processes/--process_id/--ip/--port`` into the child command
line (the reference injects ``--local_rank``, ``distributed.py:18-25``) and
propagates failures: first child to die non-zero kills the rest.

Preemption contract (docs/resilience.md): a SIGTERM to the launcher is
FORWARDED to every child — each trainer finishes its in-flight step, runs
the emergency snapshot, and exits ``PREEMPTION_EXIT_CODE`` — and the
launcher then exits with that same distinct code (75, EX_TEMPFAIL) so the
orchestrator can requeue instead of treating preemption as a crash. A
child that exits with the preemption code on its own (e.g. a per-host
SIGTERM) propagates it the same way.

Watchdog contract (docs/observability.md): ``--heartbeat_dir`` injects
ONE base ``--heartbeat_file`` into every child; each process derives its
per-rank file from it (rank 0 keeps the bare path, rank k appends
``.h<k>``) and the launcher reads the same scheme back
(``heartbeat.read``). With ``--watchdog_timeout`` set, a child
whose beat counter stops advancing for that long while the process is
still alive is WEDGED — a deadlocked collective or dead loader, which no
exit code will ever report — and the launcher says which host stalled, in
which phase and at which position, counts the stall as goodput loss, and
terminates it (SIGTERM, then SIGKILL after ``--watchdog_grace``) instead
of waiting forever. With ``--metrics_dir`` the launcher additionally
injects one base ``--metrics_file`` into every child (per-rank derived
paths, the heartbeat scheme) and the watchdog SCRAPES the wedged
worker's last OpenMetrics exposition on the way to killing it — so the
report says not just that the heartbeat froze but WHY the worker was
sick: last epoch, data-stall fraction, MFU, goodput fraction, and which
alert rules were active (docs/observability.md "Live export"). A
watchdog kill is a failure, not a preemption: the
launcher exits nonzero even if the dying child manages its graceful
exit-75, because requeueing a deterministic wedge would loop the
orchestrator on it forever. Size the timeout above the worst cold-compile
stall — the watchdog cannot tell a wedged step from one that never beat.
Once a preemption shutdown begins the watchdog stands down: children beat
once ('preempted') then go silent in the emergency save by design, and
reclassifying that as a wedge would turn the requeue-75 exit into a crash.

Crash-forensics contract (docs/observability.md "Crash forensics"):
``--crash_dir`` injects ``--crash_dir`` into every child — each rank
writes a SIGKILL-surviving flight-recorder ring and arms a faulthandler
stack-capture file (``tpu_dist/obs/flight.py``). The watchdog then
upgrades its kill sequence for a live-but-frozen rank: it first sends
``SIGUSR1`` (the registered all-threads dump), waits up to
``--watchdog_dump_grace`` for the dump to land, and names the STUCK
FRAME (loader ``get``, collective dispatch, ckpt write, ...) in the
wedge report — only then does it escalate SIGTERM→SIGKILL. After a
wedged round ends, the launcher auto-invokes the postmortem assembler
(``python -m tpu_dist.obs postmortem``) over the forensics dirs: one
bundle per incident, plus a ``postmortem`` history record appended to
the run's JSONL so ``obs tail``/``summarize``/``pod`` render the crash.
At every round spawn the launcher also sweeps per-rank files of ranks
OUTSIDE the new world (``heartbeat.sweep_stale_ranks``) — after an
elastic shrink, a departed rank's lingering heartbeat/metrics/forensics
files must not read as a dead worker.

Elastic contract (docs/resilience.md "Elastic training"): with
``--elastic_min_procs`` set, the launcher becomes its own orchestrator for
the shrink case. A round that ends preempted (exit 75) or with dead ranks
is not the end of the run: the supervisor (``tpu_dist/elastic/
supervisor.py``) counts which ranks survived (clean / 75 / forwarded-
SIGTERM exits), picks the largest feasible reduced world size (a divisor
of the original ``--nproc``, at least the floor), waits the deterministic
backoff, and relaunches the command with ``--resume`` injected and
``TPU_DIST_ELASTIC_RESTARTS`` in the environment — the trainer's elastic
restore ladder remaps the checkpoint onto the new dp extent and the
sampler re-partitions the remaining examples. Bounded by
``--elastic_max_restarts``. A SIGTERM to the LAUNCHER itself still means
"the orchestrator wants the job gone": elastic stands down and the
distinct requeue-75 code propagates as before.

Scale-up contract (docs/resilience.md "Scale-up & fleet scheduling"):
with ``--elastic_probe_interval`` set, a shrunken run does not stay
small forever. The running round polls a capacity census — the
``--elastic_capacity_file`` allocation file when given (the channel the
fleet scheduler writes), else ``TPU_DIST_AVAILABLE_PROCS``, else the
original ``--nproc`` (a dedicated host's chips "return" as soon as the
preemption ends) — at the probe interval, with a deterministic
``resilience/retry.py`` cooldown between grow decisions so a flapping
census cannot thrash the run. When the census staffs a larger feasible
divisor (bounded by ``--elastic_max_procs``), the round gracefully
SIGTERMs its own world — every rank checkpoints and exits 75 — and the
supervisor relaunches ``--resume`` at the new size; the elastic restore
ladder grows the state back bit-exactly (TD112). The same probe carries
scheduler-initiated donations (the allocation file dropped below the
current size) and caps failure relaunches (never respawn onto chips the
scheduler took away). Resizes consume no restart budget. A SIGTERM to
the launcher stands the WHOLE policy down, probe included.
"""

from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import time
from typing import Dict, List, Optional, Sequence

from tpu_dist.elastic.supervisor import RoundResult, supervise
from tpu_dist.resilience.preemption import PREEMPTION_EXIT_CODE


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(description="tpu_dist multi-process launcher")
    p.add_argument("--nproc", type=int, required=True, help="processes to spawn")
    p.add_argument("--ip", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0, help="0 = pick a free port")
    p.add_argument(
        "--devices_per_proc", type=int, default=0,
        help=">0: give each process N emulated CPU devices (testing mode)",
    )
    p.add_argument(
        "--elastic_min_procs", type=int, default=0, metavar="N",
        help="enable the elastic supervisor: when a round ends preempted "
             "(exit 75) or with dead ranks, relaunch --resume at the "
             "largest feasible reduced world size (a divisor of --nproc) "
             "instead of failing the run, never below N; 0 (default) "
             "disables — one round, exit codes as before",
    )
    p.add_argument(
        "--elastic_max_restarts", type=int, default=3, metavar="K",
        help="elastic relaunch budget: give up (surfacing the real exit "
             "code) after K relaunches — a deterministic crash loop must "
             "not cycle forever",
    )
    p.add_argument(
        "--elastic_backoff", type=float, default=0.5, metavar="S",
        help="base of the deterministic exponential backoff between "
             "elastic relaunches (resilience/retry.py schedule: "
             "S * 2^restart, capped at 30s)",
    )
    p.add_argument(
        "--elastic_probe_interval", type=float, default=0.0, metavar="S",
        help="with the elastic supervisor on: poll the capacity census "
             "every S seconds while a round runs; when it staffs a "
             "larger feasible divisor the round checkpoints (graceful "
             "SIGTERM -> exit 75) and relaunches --resume at the bigger "
             "size — a shrunken run grows back when chips return. A "
             "census below the current size is a scheduler donation: "
             "same path, smaller relaunch. 0 (default) disables probing",
    )
    p.add_argument(
        "--elastic_max_procs", type=int, default=0, metavar="N",
        help="ceiling for probe-driven grows (never above --nproc); "
             "0 (default) = --nproc",
    )
    p.add_argument(
        "--elastic_capacity_file", default=None, metavar="PATH",
        help="allocation file the capacity census reads (one integer, "
             "atomically written — the fleet scheduler's channel, "
             "tpu_dist/fleet/capacity.py); without it the census falls "
             "back to TPU_DIST_AVAILABLE_PROCS, then to --nproc",
    )
    p.add_argument(
        "--elastic_same_size_retries", type=int, default=2, metavar="K",
        help="consecutive whole-pod-loss retries at the SAME world size "
             "before the supervisor steps down one divisor (floor "
             "permitting) — one flaky round doesn't shrink the run, a "
             "persistently preempted size doesn't burn the whole budget",
    )
    p.add_argument(
        "--heartbeat_dir", default=None,
        help="inject --heartbeat_file <dir>/hb.json into every child "
             "(each process beats its own derived file: rank 0 the bare "
             "path, rank k .h<k>) and watch the files for liveness",
    )
    p.add_argument(
        "--metrics_dir", default=None,
        help="inject --metrics_file <dir>/metrics.prom into every child "
             "(per-rank derived paths, like the heartbeat) so the "
             "watchdog can scrape a wedged worker's last exposition and "
             "report WHY it was sick, not just that its beat froze",
    )
    p.add_argument(
        "--crash_dir", default=None,
        help="inject --crash_dir <dir> into every child (per-rank "
             "flight-recorder ring + faulthandler stack file, "
             "tpu_dist/obs/flight.py); the watchdog then SIGUSR1s a "
             "wedged rank for an all-threads stack dump and names the "
             "stuck frame before killing it, and a wedged round is "
             "auto-assembled into a postmortem bundle "
             "(docs/observability.md 'Crash forensics')",
    )
    p.add_argument(
        "--watchdog_dump_grace", type=float, default=5.0, metavar="S",
        help="with --crash_dir: seconds the watchdog waits for a wedged "
             "rank's SIGUSR1 stack dump to land before escalating to "
             "SIGTERM (a truly dead interpreter never answers the dump "
             "signal — the escalation must not wait on it forever)",
    )
    p.add_argument(
        "--watchdog_timeout", type=float, default=0.0, metavar="S",
        help="with --heartbeat_dir: a child whose heartbeat counter "
             "stops advancing for S seconds while the process lives is "
             "wedged — report which host/phase and terminate it instead "
             "of waiting forever; 0 disables. Must exceed the worst "
             "compile stall",
    )
    p.add_argument(
        "--watchdog_grace", type=float, default=10.0, metavar="S",
        help="seconds between the watchdog's SIGTERM and its SIGKILL",
    )
    p.add_argument("cmd", nargs=argparse.REMAINDER, help="-- command to run")
    args = p.parse_args(argv)

    cmd = list(args.cmd)
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        p.error("missing command (after --)")
    if args.watchdog_timeout > 0 and not args.heartbeat_dir:
        p.error("--watchdog_timeout needs --heartbeat_dir (the liveness "
                "signal it watches)")
    if args.elastic_min_procs > args.nproc:
        p.error(f"--elastic_min_procs {args.elastic_min_procs} exceeds "
                f"--nproc {args.nproc}")

    hb_base = None
    if args.heartbeat_dir:
        os.makedirs(args.heartbeat_dir, exist_ok=True)
        # one BASE path injected into every child; the trainer derives its
        # per-rank file from it (heartbeat.per_rank_path — rank 0 = bare
        # path, rank k = .h<k>), and the watchdog reads the same scheme
        hb_base = os.path.join(args.heartbeat_dir, "hb.json")
    metrics_base = None
    if args.metrics_dir:
        os.makedirs(args.metrics_dir, exist_ok=True)
        # same per-rank scheme as the heartbeat: the trainer derives
        # .h<k> textfiles and the watchdog scrapes them back
        metrics_base = os.path.join(args.metrics_dir, "metrics.prom")
    if args.crash_dir:
        # the dir itself is the injected flag: each rank derives its own
        # ring/stacks files inside it (obs/flight.py naming)
        os.makedirs(args.crash_dir, exist_ok=True)

    live: List[subprocess.Popen] = []  # the CURRENT round's children
    launcher_sig = [False]  # SIGTERM delivered to the LAUNCHER itself

    def _forward_sigterm(signum, frame):  # noqa: ARG001
        # graceful fan-out: children run their own SIGTERM discipline
        # (emergency snapshot + distinct exit code); we keep waiting for
        # them below instead of dying and orphaning the job. This is also
        # the elastic stand-down signal: the orchestrator preempting the
        # whole job outranks any local relaunch policy.
        launcher_sig[0] = True
        for pr in list(live):
            try:
                pr.send_signal(signal.SIGTERM)
            except OSError:  # tpu-dist: ignore[TD006] — child already gone
                pass

    try:
        prev_term = signal.signal(signal.SIGTERM, _forward_sigterm)
    except ValueError:  # not the main thread (embedded use) — skip
        prev_term = None
    try:
        def say(msg: str) -> None:
            # tpu-dist: ignore[TD002,TD007] — the launcher IS the single
            # parent process and stderr is its orchestrator contract
            print(f"launch: {msg}", file=sys.stderr, flush=True)

        probe = None
        start_procs = None
        if args.elastic_min_procs > 0 and args.elastic_probe_interval > 0:
            from tpu_dist.elastic.supervisor import (  # noqa: PLC0415
                CapacityProbe,
                next_world_size,
            )
            from tpu_dist.fleet import capacity as capacity_lib  # noqa: PLC0415

            probe = CapacityProbe(
                capacity_lib.make_census(
                    args.elastic_capacity_file, default=args.nproc
                ),
                original=args.nproc,
                min_procs=args.elastic_min_procs,
                max_procs=args.elastic_max_procs,
                interval=args.elastic_probe_interval,
            )
            # the census is authoritative from BIRTH: a run whose chips
            # are currently granted elsewhere (the fleet scheduler wrote
            # a smaller allocation before launch) must not spawn round 0
            # on top of another run and then shrink — start at the
            # granted feasible size; the probe grows it back later
            avail = probe.available()
            if avail is not None and avail < args.nproc:
                granted = next_world_size(
                    args.nproc, int(avail), args.elastic_min_procs
                )
                if granted is None:
                    say(
                        f"elastic: capacity census grants only {avail} "
                        f"proc(s) — below min_procs="
                        f"{args.elastic_min_procs}; refusing to start"
                    )
                    return 1
                say(
                    f"elastic: capacity census grants {granted} of "
                    f"{args.nproc} proc(s) at launch"
                )
                start_procs = granted

        def round_fn(nproc: int, restart: int) -> RoundResult:
            return _run_round(
                args, cmd, nproc, restart, hb_base, metrics_base,
                live, launcher_sig, probe=probe, say=say,
            )

        if args.elastic_min_procs <= 0:
            return round_fn(args.nproc, 0).rc

        return supervise(
            round_fn,
            nproc=args.nproc,
            min_procs=args.elastic_min_procs,
            max_restarts=args.elastic_max_restarts,
            backoff_base=args.elastic_backoff,
            announce=say,
            should_continue=lambda: not launcher_sig[0],
            probe=probe,
            same_size_retries=args.elastic_same_size_retries,
            start_procs=start_procs,
        )
    finally:
        if prev_term is not None:
            signal.signal(signal.SIGTERM, prev_term)
        for pr in live:
            pr.kill()


def _run_round(
    args,
    cmd: List[str],
    nproc: int,
    restart: int,
    hb_base: Optional[str],
    metrics_base: Optional[str],
    live: List[subprocess.Popen],
    launcher_sig: List[bool],
    probe=None,
    say=None,
) -> RoundResult:
    """Spawn and supervise ONE world: ``nproc`` children at a fresh
    coordinator port, fail-fast + watchdog + preemption semantics exactly
    as the single-round launcher always had. Returns the aggregate exit
    code plus every rank's raw exit status — the elastic supervisor's
    survivor census. ``live`` is the launcher-level registry the SIGTERM
    handler forwards to (children of the current round only).

    ``probe`` (a ``CapacityProbe``) arms the resize path: the wait loop
    polls it, and a census that staffs a different feasible size makes
    this round stand its own world down gracefully (SIGTERM -> every
    rank checkpoints and exits 75) and report ``resize_to`` — the
    supervisor relaunches ``--resume`` at the new size."""
    port = args.port or _free_port()
    procs: List[subprocess.Popen] = []
    ranks: Dict[subprocess.Popen, int] = {}
    exits: Dict[int, int] = {}
    preempted = [launcher_sig[0]]  # a child's exit-75 also sets this
    resize_to: List[Optional[int]] = [None]  # probe-requested new size
    announce = say if say is not None else (lambda _msg: None)
    if probe is not None:
        # a freshly spawned world always gets one full probe interval to
        # settle before the census may bounce it again
        probe.reset_timer()

    # elastic-resize hygiene: per-rank files of ranks OUTSIDE this
    # round's world (heartbeats/metrics/forensics a departed rank left
    # behind after a shrink) must be swept BEFORE spawning — a lingering
    # rank-6 heartbeat in a 4-wide world would read as a dead worker to
    # the watchdog and to `obs pod`
    from tpu_dist.obs import heartbeat as heartbeat_lib  # noqa: PLC0415

    stale_bases = [b for b in (hb_base, metrics_base) if b]
    if args.crash_dir:
        from tpu_dist.obs import flight as flight_lib  # noqa: PLC0415

        stale_bases += [
            os.path.join(args.crash_dir, flight_lib.RING_NAME),
            os.path.join(args.crash_dir, flight_lib.STACKS_NAME),
        ]
    swept = sum(
        heartbeat_lib.sweep_stale_ranks(base, nproc) for base in stale_bases
    )
    if swept:
        announce(
            f"swept {swept} stale per-rank file(s) from ranks outside "
            f"the new world of {nproc}"
        )

    # causal arbitration tracing: when this (re)launch is the actuation
    # of a fleet decision, the allocation file carries the scheduler's
    # decision_id/cause tokens — read ONCE per round and stamped into
    # every child so the trainer's resume record, flight-ring slot, and
    # goodput window can name the arbitration (stale values from the
    # launcher's own env are cleared by the stamp helper)
    from tpu_dist.elastic.supervisor import (  # noqa: PLC0415
        DECISION_CAUSE_ENV,
        DECISION_ID_ENV,
        read_decision,
    )

    meta = read_decision(getattr(args, "elastic_capacity_file", None))
    if restart > 0 and meta["decision_id"] is not None:
        announce(
            f"relaunch actuates fleet decision {meta['decision_id']}"
            + (f" ({meta['cause']})" if meta["cause"] else "")
        )

    try:
        for rank in range(nproc):
            env = dict(os.environ)
            if args.devices_per_proc > 0:
                env["PALLAS_AXON_POOL_IPS"] = ""  # CPU testing mode
                env["JAX_PLATFORMS"] = "cpu"
                env["XLA_FLAGS"] = (
                    env.get("XLA_FLAGS", "")
                    + f" --xla_force_host_platform_device_count={args.devices_per_proc}"
                ).strip()
            # relaunched rounds tell the trainer which restart they are
            # (elastic.restarts gauge); round 0 stamps 0 so a child's env
            # never inherits a stale value from the launcher's own env
            env["TPU_DIST_ELASTIC_RESTARTS"] = str(restart)
            # one meta read per ROUND (above), applied to every rank —
            # a mid-loop allocation rewrite must not split the world
            # across two decision ids
            for key, val in (
                (DECISION_ID_ENV, meta["decision_id"]),
                (DECISION_CAUSE_ENV, meta["cause"]),
            ):
                if val is not None:
                    env[key] = str(val)
                else:
                    env.pop(key, None)
            child = cmd + [
                "--num_processes", str(nproc),
                "--process_id", str(rank),
                "--ip", args.ip,
                "--port", str(port),
            ]
            if restart > 0 and "--resume" not in cmd:
                # the relaunched world must continue the run, not restart
                # it — the trainer's elastic restore ladder picks up the
                # emergency/periodic checkpoint and remaps onto the new
                # dp extent
                child.append("--resume")
            if hb_base is not None:
                child += ["--heartbeat_file", hb_base]
            if metrics_base is not None:
                child += ["--metrics_file", metrics_base]
            if args.crash_dir is not None:
                child += ["--crash_dir", args.crash_dir]
            pr = subprocess.Popen(child, env=env)
            procs.append(pr)
            live.append(pr)
            ranks[pr] = rank

        rc = 0
        crash_rc = 0  # first exit that is neither clean, preemption, nor
        # death-by-our-own-SIGTERM — a REAL failure that must never be
        # reported as "requeue me"
        # watchdog state per rank: last seen beat counter, when it last
        # advanced (spawn counts as the first advance — a child that never
        # beats at all is as wedged as one that stopped), and the SIGKILL
        # deadline once the watchdog fired
        now = time.monotonic()
        wd_seen: Dict[int, tuple] = {ranks[pr]: (None, now) for pr in procs}
        wd_kill_at: Dict[int, float] = {}
        # stack-capture state (--crash_dir): rank -> [dump deadline,
        # stack-file size before SIGUSR1, size at the last poll] — the
        # watchdog waits for the dump to land AND settle before it
        # parses the appended bytes and escalates
        wd_dump: Dict[int, list] = {}
        wedged: List[int] = []  # ranks the watchdog declared wedged
        watchdog = args.watchdog_timeout > 0

        def _stack_path(rank: int) -> Optional[str]:
            if not args.crash_dir:
                return None
            from tpu_dist.obs import flight as flight_lib  # noqa: PLC0415
            from tpu_dist.obs import heartbeat as heartbeat_lib  # noqa: PLC0415

            return heartbeat_lib.per_rank_path(
                os.path.join(args.crash_dir, flight_lib.STACKS_NAME), rank
            )

        def _stack_size(rank: int) -> int:
            path = _stack_path(rank)
            try:
                return os.path.getsize(path) if path else 0
            except OSError:
                return 0

        def _sick_report(rank: int) -> str:
            """WHY the wedged worker was sick: its last OpenMetrics
            exposition (the exporter leaves the textfile behind exactly
            for this read). Empty string when nothing is scrapeable —
            the watchdog's heartbeat-only report still stands."""
            if metrics_base is None:
                return ""
            from tpu_dist.obs import export as export_lib  # noqa: PLC0415
            from tpu_dist.obs import heartbeat as heartbeat_lib  # noqa: PLC0415

            vals = export_lib.scrape(
                textfile=heartbeat_lib.per_rank_path(metrics_base, rank)
            )
            if not vals:
                return ""
            # ONE gauge set shared with the postmortem assembler
            # (export.KEY_GAUGES) — the two reads can never drift
            parts = [
                f"{label} {v}"
                for label, v in export_lib.key_gauges(vals).items()
            ]
            active = export_lib.active_labels(vals)
            if active:
                parts.append(f"active alerts: {', '.join(active)}")
            return (
                f"; last exposition: {', '.join(parts)}" if parts else ""
            )

        def _watch(pr) -> None:
            nonlocal crash_rc
            from tpu_dist.obs import heartbeat as heartbeat_lib  # noqa: PLC0415

            if preempted[0] or launcher_sig[0] or resize_to[0] is not None:
                # preemption/resize shutdown: each child beats once
                # ('preempted') then goes silent in its emergency save BY
                # DESIGN — a frozen counter here is not a wedge, and
                # reclassifying it would turn the requeue-75 exit into a
                # crash. A truly stuck shutdown is bounded by the
                # platform's own SIGKILL deadline, not by us.
                return
            rank = ranks[pr]
            t = time.monotonic()
            if rank in wd_kill_at:
                if t >= wd_kill_at[rank]:
                    pr.kill()  # SIGTERM grace expired — it really is stuck
                return
            if rank in wd_dump:
                # stack capture in flight: wait for the SIGUSR1 dump to
                # land and settle (two same-size polls), bounded by the
                # dump grace — a dead interpreter never answers
                deadline, size0, last_size = wd_dump[rank]
                size = _stack_size(rank)
                if t < deadline and (size <= size0 or size != last_size):
                    wd_dump[rank][2] = size
                    return
                from tpu_dist.obs import flight as flight_lib  # noqa: PLC0415

                parsed = (
                    flight_lib.read_stack_dump(_stack_path(rank), offset=size0)
                    if size > size0 else None
                )
                frame = flight_lib.stuck_frame(parsed) if parsed else None
                # tpu-dist: ignore[TD002,TD007] — the launcher IS the
                # single parent process; stderr is its orchestrator
                # contract (same as the wedge report above)
                print(
                    f"launch: WATCHDOG: worker {rank} stack dump: "
                    + (
                        f"stuck in {frame} "
                        f"({len(parsed['threads'])} thread(s) dumped)"
                        if frame else
                        "no dump captured (interpreter not answering "
                        "SIGUSR1 — likely stuck in native code)"
                    ),
                    file=sys.stderr, flush=True,
                )
                del wd_dump[rank]
                wd_kill_at[rank] = t + args.watchdog_grace
                try:
                    pr.send_signal(signal.SIGTERM)
                except OSError:  # tpu-dist: ignore[TD006] — child gone
                    pass
                return
            rec = heartbeat_lib.read(heartbeat_lib.per_rank_path(hb_base, rank))
            counter = rec.get("counter") if rec else None
            last_counter, last_adv = wd_seen[rank]
            if counter != last_counter:
                wd_seen[rank] = (counter, t)
                return
            stalled = t - last_adv
            if stalled < args.watchdog_timeout:
                return
            # wedged: alive but silent — no exit code would ever tell us
            where = (
                f"epoch {rec.get('epoch')} step {rec.get('step')} phase "
                f"{rec.get('phase')!r}" if rec else "before its first beat"
            )
            # tpu-dist: ignore[TD002,TD007] — the launcher IS the single
            # parent process (no ranks to guard), and stderr is its
            # contract with the orchestrator, same as the exit codes
            print(
                f"launch: WATCHDOG: worker {rank} wedged — heartbeat "
                f"stalled {stalled:.0f}s at {where}; terminating "
                f"(~{stalled:.0f}s goodput loss on this host)"
                + _sick_report(rank),
                file=sys.stderr, flush=True,
            )
            if crash_rc == 0:
                crash_rc = 1  # a wedge is a failure, never a requeue-75
            wedged.append(rank)
            if args.crash_dir:
                # stack capture FIRST: ask the frozen-but-live interpreter
                # WHERE it is (the rank's faulthandler registered SIGUSR1
                # as an all-threads dump) — the kill escalation waits for
                # the answer, bounded by --watchdog_dump_grace
                size0 = _stack_size(rank)
                try:
                    pr.send_signal(signal.SIGUSR1)
                except OSError:  # tpu-dist: ignore[TD006] — child gone
                    pass
                wd_dump[rank] = [t + args.watchdog_dump_grace, size0, size0]
                # tpu-dist: ignore[TD002,TD007] — launcher stderr contract
                print(
                    f"launch: WATCHDOG: requesting all-threads stack dump "
                    f"from worker {rank} (SIGUSR1), waiting up to "
                    f"{args.watchdog_dump_grace:.0f}s before escalating",
                    file=sys.stderr, flush=True,
                )
                return
            wd_kill_at[rank] = t + args.watchdog_grace
            try:
                pr.send_signal(signal.SIGTERM)
            except OSError:  # tpu-dist: ignore[TD006] — child already gone
                pass

        pending = list(procs)
        while pending:
            if (
                probe is not None and resize_to[0] is None
                and not preempted[0] and not launcher_sig[0]
                and crash_rc == 0
            ):
                target = probe.poll(nproc)
                if target is not None and target != nproc:
                    # capacity changed: stand this world down gracefully —
                    # every rank checkpoints (emergency save) and exits 75,
                    # and the supervisor relaunches --resume at the target
                    resize_to[0] = target
                    announce(
                        "elastic: capacity census wants world size "
                        f"{target} (running {nproc}) — checkpointing this "
                        "round for the resize"
                    )
                    for pr in list(pending):
                        try:
                            pr.send_signal(signal.SIGTERM)
                        except OSError:  # tpu-dist: ignore[TD006] — child gone
                            pass
            for pr in list(pending):
                ret = pr.poll()
                if ret is None:
                    if watchdog:
                        _watch(pr)
                    continue
                pending.remove(pr)
                exits[ranks[pr]] = ret
                if ret == PREEMPTION_EXIT_CODE:
                    preempted[0] = True
                elif ret not in (0, -signal.SIGTERM) and crash_rc == 0:
                    crash_rc = ret
                if ret != 0 and rc == 0:
                    rc = ret
                    for other in pending:  # fail fast like torchrun — which,
                        # with the trainer's cooperative handler installed,
                        # is a GRACEFUL shutdown request, not a kill
                        other.send_signal(signal.SIGTERM)
            if pending:
                try:
                    pending[0].wait(timeout=1)
                except subprocess.TimeoutExpired:
                    pass
        if wedged and args.crash_dir:
            # the forensic epilogue: assemble everything the dead world
            # left behind into ONE bundle + a `postmortem` history record
            # (obs tail/summarize/pod render it). Never raises — a broken
            # postmortem must not change the exit-code contract.
            _auto_postmortem(args, announce, wedged)
        if crash_rc:
            # a crash/wedge outranks a concurrent preemption AND a resize
            # request (the supervisor's failure path must see the real
            # census, not a voluntary-looking resize)
            return RoundResult(crash_rc, exits)
        if (
            preempted[0] or launcher_sig[0]
            or (resize_to[0] is not None and rc != 0)
        ) and rc in (0, PREEMPTION_EXIT_CODE, -signal.SIGTERM):
            # the whole job was preempted (not crashed): surface the
            # distinct requeue-me code even if some child died on the raw
            # signal before its handler was installed. A probe-driven
            # resize rides this same path (graceful 75s) and carries its
            # target so the supervisor relaunches instead of retrying.
            return RoundResult(PREEMPTION_EXIT_CODE, exits, resize_to[0])
        return RoundResult(rc, exits)
    finally:
        for pr in procs:
            pr.kill()  # no-op on already-reaped children
            if pr in live:
                live.remove(pr)


def _auto_postmortem(args, say, wedged: List[int]) -> None:
    """Watchdog epilogue: run the postmortem assembler over every
    forensics dir this launcher injected, write the bundle, annotate the
    run's history (when one is discoverable), and summarize the wedged
    ranks on stderr. Best-effort by contract."""
    from tpu_dist.obs import postmortem as postmortem_lib  # noqa: PLC0415

    dirs = [
        d for d in (args.crash_dir, args.heartbeat_dir, args.metrics_dir)
        if d
    ]
    try:
        report, bundle = postmortem_lib.run_postmortem(dirs, annotate=True)
    except Exception as e:
        say(f"postmortem assembly failed: {e}")
        return
    if bundle is None:
        say("postmortem: no forensic artifacts found")
        return
    say(f"postmortem bundle written to {bundle}")
    for r in report["ranks"]:
        if r["rank"] not in wedged:
            continue
        stuck = (r.get("stack") or {}).get("stuck_frame")
        ls = (r.get("flight") or {}).get("last_step")
        say(
            f"postmortem: rank {r['rank']} verdict {r['verdict']}"
            + (f", stuck in {stuck}" if stuck else "")
            + (
                f", flight ring ends at epoch {ls.get('epoch')} step "
                f"{ls.get('step')}" if ls else ""
            )
        )


if __name__ == "__main__":
    sys.exit(main())
