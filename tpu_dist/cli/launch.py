"""Local multi-process launcher — ``torch.distributed.launch`` / ``torchrun``
equivalent (SURVEY §2.2 N8).

On real TPU pods you normally run ONE process per host and the TPU runtime
does slice discovery, so this launcher exists for two cases the reference's
launchers cover:

* spinning up a multi-process run on one machine (CPU emulation of
  multi-host — each process gets its own device set via
  ``--xla_force_host_platform_device_count``),
* explicitly-coordinated multi-host setups where you want rank/env control
  (`--node_rank`-style splits).

Usage::

    python -m tpu_dist.cli.launch --nproc 2 --devices_per_proc 4 -- \
        python -m tpu_dist.cli.train --dataset synthetic --epochs 1

Injects ``--num_processes/--process_id/--ip/--port`` into the child command
line (the reference injects ``--local_rank``, ``distributed.py:18-25``) and
propagates failures: first child to die non-zero kills the rest.

Preemption contract (docs/resilience.md): a SIGTERM to the launcher is
FORWARDED to every child — each trainer finishes its in-flight step, runs
the emergency snapshot, and exits ``PREEMPTION_EXIT_CODE`` — and the
launcher then exits with that same distinct code (75, EX_TEMPFAIL) so the
orchestrator can requeue instead of treating preemption as a crash. A
child that exits with the preemption code on its own (e.g. a per-host
SIGTERM) propagates it the same way.
"""

from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
from typing import List, Optional, Sequence

from tpu_dist.resilience.preemption import PREEMPTION_EXIT_CODE


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(description="tpu_dist multi-process launcher")
    p.add_argument("--nproc", type=int, required=True, help="processes to spawn")
    p.add_argument("--ip", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0, help="0 = pick a free port")
    p.add_argument(
        "--devices_per_proc", type=int, default=0,
        help=">0: give each process N emulated CPU devices (testing mode)",
    )
    p.add_argument("cmd", nargs=argparse.REMAINDER, help="-- command to run")
    args = p.parse_args(argv)

    cmd = list(args.cmd)
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        p.error("missing command (after --)")
    port = args.port or _free_port()

    procs: List[subprocess.Popen] = []
    preempted = [False]

    def _forward_sigterm(signum, frame):  # noqa: ARG001
        # graceful fan-out: children run their own SIGTERM discipline
        # (emergency snapshot + distinct exit code); we keep waiting for
        # them below instead of dying and orphaning the job
        preempted[0] = True
        for pr in list(procs):
            try:
                pr.send_signal(signal.SIGTERM)
            except OSError:  # tpu-dist: ignore[TD006] — child already gone
                pass

    try:
        prev_term = signal.signal(signal.SIGTERM, _forward_sigterm)
    except ValueError:  # not the main thread (embedded use) — skip
        prev_term = None
    try:
        for rank in range(args.nproc):
            env = dict(os.environ)
            if args.devices_per_proc > 0:
                env["PALLAS_AXON_POOL_IPS"] = ""  # CPU testing mode
                env["JAX_PLATFORMS"] = "cpu"
                env["XLA_FLAGS"] = (
                    env.get("XLA_FLAGS", "")
                    + f" --xla_force_host_platform_device_count={args.devices_per_proc}"
                ).strip()
            child = cmd + [
                "--num_processes", str(args.nproc),
                "--process_id", str(rank),
                "--ip", args.ip,
                "--port", str(port),
            ]
            procs.append(subprocess.Popen(child, env=env))

        rc = 0
        crash_rc = 0  # first exit that is neither clean, preemption, nor
        # death-by-our-own-SIGTERM — a REAL failure that must never be
        # reported as "requeue me"
        while procs:
            for pr in list(procs):
                ret = pr.poll()
                if ret is None:
                    continue
                procs.remove(pr)
                if ret == PREEMPTION_EXIT_CODE:
                    preempted[0] = True
                elif ret not in (0, -signal.SIGTERM) and crash_rc == 0:
                    crash_rc = ret
                if ret != 0 and rc == 0:
                    rc = ret
                    for other in procs:  # fail fast like torchrun — which,
                        # with the trainer's cooperative handler installed,
                        # is a GRACEFUL shutdown request, not a kill
                        other.send_signal(signal.SIGTERM)
            if procs:
                try:
                    procs[0].wait(timeout=1)
                except subprocess.TimeoutExpired:
                    pass
        if crash_rc:
            return crash_rc  # a crash outranks a concurrent preemption
        if preempted[0] and rc in (0, PREEMPTION_EXIT_CODE, -signal.SIGTERM):
            # the whole job was preempted (not crashed): surface the
            # distinct requeue-me code even if some child died on the raw
            # signal before its handler was installed
            return PREEMPTION_EXIT_CODE
        return rc
    finally:
        if prev_term is not None:
            signal.signal(signal.SIGTERM, prev_term)
        for pr in procs:
            pr.kill()


if __name__ == "__main__":
    sys.exit(main())
