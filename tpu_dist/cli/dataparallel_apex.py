"""DP + AMP preset (reference ``dataparallel_apex.py``: ``amp.initialize`` at
``:53``, ``amp.scale_loss`` at ``:86-87``). AMP ≡ bf16 compute policy on TPU
(no loss scaling needed — bf16 has fp32's exponent range)."""

from tpu_dist.cli.train import main as _main


def main(argv=None):
    _main(argv, bf16=True)


if __name__ == "__main__":
    main()
