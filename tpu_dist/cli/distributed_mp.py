"""Spawn-mode DDP preset (reference ``distributed_mp.py``, the repo's
recommended path, ``README.md:197-198``). There is nothing to spawn on TPU —
one process per host already owns all local chips — so this differs from
``distributed`` only in enabling the reference's per-rank deterministic
seeding (``init_seeds(local_rank+1)``, ``distributed_mp.py:29-39,56``) by
defaulting ``--seed 1``."""

from tpu_dist.cli.train import main as _main


def main(argv=None):
    import sys

    argv = list(sys.argv[1:] if argv is None else argv)
    if not any(a.startswith("--seed") for a in argv):
        argv += ["--seed", "1"]
    _main(argv)


if __name__ == "__main__":
    main()
