"""One shared config layer: dataclass + argparse bridge.

Replaces both the per-script argparse blocks the reference duplicates six
times (``distributed.py:18-25``, ``dataparallel.py:18-23``,
``distributed_gradient_accumulation.py:26``) and the dead ``global_config``
(``utils/config.py:1-10``, never imported). Every reference flag is
preserved; ``--ip/--port`` become the multi-host coordinator address
(rendezvous is slice discovery on TPU, SURVEY §3.5).
"""

from __future__ import annotations

import argparse
import dataclasses
from dataclasses import dataclass
from typing import Optional, Tuple


@dataclass
class TrainConfig:
    # -- reference flags (distributed.py:18-25) -----------------------------
    batch_size: int = 256          # GLOBAL batch; per-replica = batch_size / n_devices
    epochs: int = 200
    lr: float = 0.1
    seed: Optional[int] = None     # per-rank seeding when set (distributed_mp.py:29-39)
    ip: str = "127.0.0.1"          # coordinator host (was hard-coded 10.24.82.29)
    port: int = 23456              # coordinator port
    grad_accu_steps: int = 1       # distributed_gradient_accumulation.py:26

    # -- optimizer / schedule (hard-coded in the reference) -----------------
    optimizer: str = "sgd"         # sgd (reference, distributed.py:63) |
                                   # adamw | lars | lamb (large-batch
                                   # trust-ratio recipes, train/optim.py)
    momentum: float = 0.9          # distributed.py:63 (sgd/lars)
    weight_decay: float = 1e-4     # distributed.py:63
    adamw_decay_mask: str = "auto" # auto: skip rank<=1 leaves | all: decay every leaf
    lr_schedule: str = "multistep" # multistep (reference) | cosine
    lr_milestones: Tuple[int, ...] = (60, 120, 160)  # distributed.py:64
    lr_gamma: float = 0.2          # distributed.py:64
    warmup_epochs: int = 0         # linear LR warmup epochs (both schedules)
    lr_base_batch: int = 0         # Goyal linear-scaling rule: when > 0,
                                   # lr is scaled by batch_size/lr_base_batch
                                   # (optim.linear_scaled_lr — the
                                   # large-batch LARS/LAMB recipe)
    label_smoothing: float = 0.0
    grad_clip_norm: float = 0.0    # 0 = off; global-norm clip of reduced grads

    # -- TPU-native switches (replace whole reference scripts) --------------
    bf16: bool = False             # apex AMP path (distributed_apex.py) → bf16 policy
    sync_bn: bool = True           # SyncBN on by default (README.md:62)
    drop_last: bool = False        # grad-accum path uses True (…accumulation.py:71)

    # -- data ---------------------------------------------------------------
    dataset: str = "cifar100"      # cifar100 | cifar10 | synthetic
    data_dir: str = "./data"
    synthetic_n: int = 50_000      # synthetic train-set size (tests/smokes)
    num_workers: int = 4           # loader prefetch depth (passed to DataLoader)

    # -- model --------------------------------------------------------------
    model: str = "resnet18"        # resnet18 | resnet34 | resnet50 | vit_b16
    num_classes: int = 100

    # -- multi-host ---------------------------------------------------------
    num_processes: Optional[int] = None
    process_id: Optional[int] = None

    # -- mesh shape ----------------------------------------------------------
    sp: int = 1                    # sequence-parallel ways (DPxSP mesh);
                                   # model must support seq_axis (ViT)
    sp_mode: str = "ring"          # 'ring' (ppermute K/V rotation) or
                                   # 'ulysses' (all_to_all tokens<->heads)
    tp: int = 1                    # tensor-parallel ways (DPxTP mesh);
                                   # model must support tp_axis (ViT)
    ep: int = 1                    # expert-parallel ways (DPxEP mesh);
                                   # model must support ep_axis (ViT-MoE)
    moe_top_k: int = 1             # experts per token (1=Switch, 2=GShard)
    moe_aux_coef: float = 0.01     # router load-balancing loss coefficient
    pp: int = 1                    # pipeline-parallel stages (DPxPP mesh);
                                   # model must support pp_axis (ViT-PP)
    pp_microbatches: int = 0       # 0 = one microbatch per stage
    pp_interleave: int = 1         # virtual stages per device (Megatron
                                   # interleaved schedule: bubble shrinks
                                   # (S-1)/(M+S-1) -> (S-1)/(vM+S-1))

    # -- checkpoint / eval cadence -----------------------------------------
    ckpt_dir: Optional[str] = None
    save_every: int = 15           # dead utils/config.py:7 'save_epoch', made real
    keep_last_ckpts: Optional[int] = None  # prune to N newest (None = keep all)
    mid_epoch_save_every: int = 0  # >0: periodic EXACT snapshots every N steps
                                   # inside an epoch (kill-9 safety for long
                                   # epochs; resume re-enters at the batch)
    resume: bool = False
    async_ckpt: bool = False       # overlap ckpt writes with training
                                   # (ckpt/checkpoint.py::AsyncCheckpointer;
                                   # with --sharded_ckpt: the snapshot-then-
                                   # write AsyncShardedCheckpointer)
    ckpt_drain_timeout_s: float = 120.0  # bounded drain of in-flight async
                                   # ckpt writes at fit end / interrupt;
                                   # expiry abandons them LOUDLY (counted
                                   # as ckpt.drain_abandoned); <=0 = wait
                                   # forever
    eval_every: int = 1
    log_every: int = 20
    log_file: Optional[str] = None # JSONL metrics history (rank 0)
    tensorboard_dir: Optional[str] = None  # the reference's dead
                                   # utils/config.py:8 knob, made real
                                   # (metrics/tensorboard.py, rank 0)

    # -- run telemetry (docs/observability.md) ------------------------------
    trace_file: Optional[str] = None  # Chrome trace-event JSON of host
                                   # spans (ckpt/loader/eval/dispatch),
                                   # Perfetto-loadable; rank 0. Spans are
                                   # also armed when log_file is set (they
                                   # ride the JSONL as 'spans' records)
    heartbeat_file: Optional[str] = None  # per-process liveness file (rank
                                   # 0 the bare path, rank k .h<k>) updated
                                   # at the step grain (monotonic counter +
                                   # epoch/step); swept on clean exit —
                                   # external watchdogs distinguish a hung
                                   # step from a slow one
    straggler_threshold: float = 1.5  # epoch-end max/median skew of the
                                   # allgathered per-process epoch times
                                   # above which a rank-0 straggler warning
                                   # (+ history record) fires; 0 disables
    device_metrics: bool = False   # in-step health scalars (global grad
                                   # norm, param norm, update ratio,
                                   # nonfinite-leaf count) fused into the
                                   # traced step post-pmean — zero extra
                                   # collectives/fetches (TD107;
                                   # obs/device_stats.py). Replicated-
                                   # param paths only (no zero1/fsdp/
                                   # tp/ep/pp/fused_epoch)
    anomaly_action: str = "warn"   # off | warn | snapshot — response to a
                                   # rolling-window loss-spike/grad-norm
                                   # anomaly (obs/anomaly.py): warn logs a
                                   # rank-0 warning + 'anomaly' history
                                   # record; snapshot additionally writes
                                   # an exact mid-epoch checkpoint
    anomaly_window: int = 50       # rolling-median window (observations at
                                   # the log cadence)
    anomaly_loss_spike: float = 3.0   # loss > X * rolling median => anomaly
    anomaly_grad_spike: float = 10.0  # grad_norm > X * rolling median
                                   # (needs --device_metrics for the norm)
    metrics_file: Optional[str] = None  # live OpenMetrics textfile
                                   # (node-exporter textfile-collector
                                   # format), written atomically at the
                                   # heartbeat's step-grain throttle;
                                   # per-rank derived path like the
                                   # heartbeat (obs/export.py)
    metrics_port: int = 0          # rank-0-only background HTTP /metrics
                                   # endpoint serving the last rendered
                                   # snapshot (never touches jax state
                                   # from the serving thread); 0 disables
    alert_rules: Optional[str] = None  # declarative threshold alerting:
                                   # 'default' (built-in library) or a
                                   # TOML/JSON rule-spec path — fired
                                   # rules emit 'alert' history records,
                                   # rank-0 warnings, exporter gauge
                                   # flips, and optionally arm the
                                   # triggered profiler (obs/alerts.py)
    crash_dir: Optional[str] = None  # crash-forensics dir (docs/
                                   # observability.md "Crash forensics"):
                                   # per-rank SIGKILL-surviving flight-
                                   # recorder ring (flight.ring[.h<k>],
                                   # fixed-slot atomic writes at the step
                                   # grain) + faulthandler stack-dump
                                   # file (stacks.txt[.h<k>]: hard-fault
                                   # tracebacks, SIGUSR1 on-demand
                                   # all-threads dumps); read back by
                                   # `python -m tpu_dist.obs postmortem`
    memory_check: str = "warn"     # off | warn | refuse — pre-flight HBM
                                   # feasibility lint (obs/memory.py):
                                   # the static per-leaf ledger (params/
                                   # opt-state/EF/BN/batch at sharded
                                   # extents) is priced against the
                                   # per-chip HBM budget BEFORE the
                                   # first compile; 'refuse' raises
                                   # InfeasibleMemoryError, 'warn'
                                   # prints. Unknown chips (CPU
                                   # emulation) skip the check unless
                                   # hbm_budget_bytes overrides
    memory_headroom: float = 0.9   # fraction of the per-chip budget the
                                   # STATIC estimate may claim — the
                                   # rest is reserved for XLA temps/
                                   # workspace the ledger cannot see
    hbm_budget_bytes: Optional[int] = None  # per-device HBM budget
                                   # override (default: the chip table,
                                   # costmodel.CHIP_HBM_BYTES); lets CPU
                                   # tests and exotic parts drive the
                                   # feasibility lint
    per_host_log: bool = False     # every process writes its own JSONL
                                   # history (<log_file>.h<rank>; rank 0
                                   # keeps the bare path) so `obs pod`
                                   # can merge a cross-host view
    profile_trigger: str = "off"   # off | auto | comma list of
                                   # anomaly,straggler,retrace — arm a
                                   # bounded jax.profiler capture when
                                   # the health signal fires
                                   # (obs/profile.py; needs profile_dir)
    profile_steps: Optional[str] = None  # "a:b": manual capture of global
                                   # steps [a, b) (needs profile_dir;
                                   # replaces the epoch-0 blanket trace)
    profile_window: int = 8        # steps per triggered capture
    profile_cooldown: int = 200    # min steps between triggered captures
    profile_max_captures: int = 3  # triggered-capture cap per process

    # -- TPU fast path -------------------------------------------------------
    fused_epoch: bool = False      # device-resident data, one jit per epoch
                                   # (docs in train/epoch.py; small datasets)
    shard_weight_update: bool = False  # ZeRO-1 weight-update sharding
                                       # (arXiv:2004.13336; train/step.py)
    fsdp: bool = False             # fully-sharded (ZeRO-3) params+momentum
                                   # via GSPMD (parallel/fsdp.py)
    fused_optimizer: bool = False  # Pallas fused SGD kernel (ops/fused_sgd.py)
    flash_attention: bool = False  # Pallas tiled attention (ops/flash_attention.py)
                                   # for transformer models; process-global
    remat: bool = False            # jax.checkpoint the forward (less memory)
    grad_compression: str = "none" # none | bf16 | int8 | int8_ef: gradient
                                   # wire format for the cross-replica reduce
                                   # (DDP comm-hook equivalent). bf16 halves
                                   # grad ICI/DCN traffic; int8 quarters it
                                   # (per-chunk scales, stochastic rounding,
                                   # two-stage quantized RS+AG); int8_ef adds
                                   # error-feedback residuals in TrainState
                                   # (docs/compression.md)
    quant_chunk: int = 0           # elements per int8 quantization scale
                                   # (0 = comm/quantize.DEFAULT_CHUNK); a
                                   # tune-overlap schedule knob — payload
                                   # bytes are chunk-invariant (TD121)
    pmean_fusion: str = "fused"    # fused | per_leaf: one multi-operand grad
                                   # pmean vs one per leaf — schedule-only
                                   # overlap knob (analysis/overlap.py)
    rs_ag_chunks: int = 1          # split the ZeRO-1 reduce-scatter/all-
                                   # gather pair into k pipelined column-
                                   # group collectives (payload-identical;
                                   # tune-overlap's zero1 knob)
    tune_report: str = ""          # path to a tune_report.json (make
                                   # tune-overlap): apply the tuner's chosen
                                   # schedule knobs for this config's family
                                   # (explicit knob flags win over the report)
    sharded_ckpt: bool = False     # per-process shard files + rank-0 manifest;
                                   # no gather at save time (FSDP/ZeRO scale)
    auto_shard: str = "off"        # off | plan | apply — run the static
                                   # sharding planner (analysis/planner.py)
                                   # at startup: enumerate the shardlint
                                   # family matrix, price each with the
                                   # calibrated cost model, refuse HBM-
                                   # infeasible configs through the
                                   # --memory_check path, print the ranked
                                   # table. 'apply' additionally rewrites
                                   # this config to the chosen plan's
                                   # family (docs/planner.md)

    # -- resilience (docs/resilience.md) ------------------------------------
    ckpt_verify: bool = True       # CRC32-verify checkpoints at restore and
                                   # walk newest→oldest past quarantined
                                   # (*.corrupt) files instead of raising
    ckpt_io_retries: int = 2       # transient ckpt-write retries (exponential
                                   # backoff, deterministic delays; 0 = off)
    fault_plan: Optional[str] = None  # deterministic fault-injection spec
                                   # (chaos testing; env TPU_DIST_FAULT_PLAN
                                   # when unset — resilience/faults.py)

    # -- bench / smoke / debug ---------------------------------------------
    steps_per_epoch: Optional[int] = None  # cap steps (smoke tests / benches)
    debug_replica_check: bool = False  # assert params replicated each epoch
    profile_dir: Optional[str] = None  # capture an XLA trace of epoch 0
    nan_guard: bool = True         # raise TrainingDivergedError on NaN loss
    auto_recover: int = 0          # divergence responses: reload last ckpt +
                                   # LR backoff, up to N times (0 = just raise)
    recover_lr_factor: float = 0.5 # schedule scale applied per recovery
    compile_cache_dir: Optional[str] = None  # persistent XLA compile cache:
                                   # repeat invocations of the same config
                                   # skip the cold first-compile. NOTE:
                                   # applied as PROCESS-GLOBAL jax.config
                                   # state (XLA's cache is per-process) —
                                   # it persists for later Trainers in the
                                   # same process

    @property
    def coordinator_address(self) -> str:
        return f"{self.ip}:{self.port}"

    def replace(self, **kw) -> "TrainConfig":
        return dataclasses.replace(self, **kw)


def add_reference_flags(p: argparse.ArgumentParser) -> argparse.ArgumentParser:
    d = TrainConfig()
    p.add_argument("--batch_size", "--batch-size", type=int, default=d.batch_size,
                   help="GLOBAL batch size (split across data-parallel devices)")
    p.add_argument("--epochs", type=int, default=d.epochs)
    p.add_argument("--lr", type=float, default=d.lr)
    p.add_argument("--seed", type=int, default=None,
                   help="deterministic seeding (reference init_seeds semantics)")
    p.add_argument("--ip", type=str, default=d.ip,
                   help="multi-host coordinator address (reference --ip)")
    p.add_argument("--port", type=int, default=d.port)
    p.add_argument("--grad_accu_steps", type=int, default=d.grad_accu_steps,
                   help="gradient accumulation sub-steps (no_sync semantics)")
    p.add_argument("--optimizer", choices=("sgd", "adamw", "lars", "lamb"),
                   default=d.optimizer,
                   help="sgd (reference parity), adamw (decoupled weight "
                        "decay; the transformer default), or the large-batch "
                        "trust-ratio recipes: lars (layer-wise adaptive SGD, "
                        "conv nets at 16k+ batch) and lamb (layer-wise "
                        "AdamW, BERT-style) — pair with --lr_base_batch and "
                        "--warmup_epochs")
    p.add_argument("--momentum", type=float, default=d.momentum)
    p.add_argument("--weight_decay", type=float, default=d.weight_decay)
    p.add_argument("--adamw_decay_mask", choices=("auto", "all"),
                   default=d.adamw_decay_mask,
                   help="adamw only: 'auto' (default) skips weight decay on "
                        "rank<=1 leaves (biases/norm scales, standard "
                        "transformer practice); 'all' decays every leaf "
                        "(pre-r3 behavior — use when resuming a pre-r3 "
                        "adamw run)")
    p.add_argument("--lr_schedule", choices=("multistep", "cosine"), default=d.lr_schedule)
    p.add_argument("--lr_milestones", type=int, nargs="+",
                   default=list(d.lr_milestones), metavar="EPOCH",
                   help="multistep decay epochs (reference hard-codes "
                        "[60, 120, 160], distributed.py:64)")
    p.add_argument("--lr_gamma", type=float, default=d.lr_gamma,
                   help="multistep decay factor (reference: 0.2)")
    p.add_argument("--warmup_epochs", type=int, default=d.warmup_epochs,
                   help="linear LR warmup epochs (cosine and multistep; "
                        "mandatory half of the large-batch LARS/LAMB recipe)")
    p.add_argument("--lr_base_batch", type=int, default=d.lr_base_batch,
                   metavar="B0",
                   help="Goyal linear-scaling rule: scale --lr by "
                        "batch_size/B0 (0 = off). The other half of the "
                        "large-batch recipe")
    p.add_argument("--label_smoothing", type=float, default=d.label_smoothing)
    p.add_argument("--grad_clip_norm", type=float, default=d.grad_clip_norm,
                   help="global-norm gradient clip; 0 disables")
    p.add_argument("--bf16", action="store_true",
                   help="bf16 compute policy (the apex-AMP equivalent)")
    p.add_argument("--fused_epoch", action="store_true",
                   help="device-resident data: one jit call per epoch")
    p.add_argument("--shard_weight_update", "--zero1", action="store_true",
                   help="ZeRO-1 weight-update sharding (arXiv:2004.13336), "
                        "sgd or adamw; plain-DP fast path by design — use "
                        "--fsdp for model-parallel compositions")
    p.add_argument("--fsdp", action="store_true",
                   help="fully-sharded data parallelism (ZeRO-3): params and "
                        "momentum sharded over the data axis via GSPMD")
    p.add_argument("--fused_optimizer", action="store_true",
                   help="Pallas fused SGD kernel")
    p.add_argument("--flash_attention", action="store_true",
                   help="Pallas tiled (flash) attention for transformer "
                        "models — O(block^2) memory instead of O(S^2)")
    p.add_argument("--remat", action="store_true",
                   help="jax.checkpoint the forward (less activation memory)")
    p.add_argument("--grad_compression",
                   choices=("none", "bf16", "int8", "int8_ef"),
                   default=d.grad_compression,
                   help="gradient wire format for the cross-replica reduce "
                        "(torch DDP communication-hook equivalent; update "
                        "math stays f32): bf16 halves gradient ICI/DCN "
                        "traffic; int8 quarters it via per-chunk scaled "
                        "stochastic-rounded quantization on BOTH legs of a "
                        "two-stage reduce-scatter + all-gather (EQuARX-"
                        "style); int8_ef adds per-replica error-feedback "
                        "residuals (carried in the TrainState, "
                        "checkpointed) so quantization error is "
                        "compensated, not accumulated. int8 modes apply to "
                        "the plain DP, fused-epoch, and ZeRO-1 paths; not "
                        "under --fsdp (GSPMD-inserted collectives) or "
                        "sp/tp/ep/pp (docs/compression.md)")
    p.add_argument("--quant_chunk", type=int, default=d.quant_chunk,
                   metavar="N",
                   help="elements per int8 quantization scale (0 = the "
                        "comm/quantize default) — a tune-overlap schedule "
                        "knob: payload bytes are chunk-invariant, only the "
                        "f32 scale sideband granularity moves (TD121)")
    p.add_argument("--pmean_fusion", choices=("fused", "per_leaf"),
                   default=d.pmean_fusion,
                   help="data-parallel grad reduce granularity: one fused "
                        "multi-operand pmean, or one pmean per gradient "
                        "leaf (schedule-only overlap knob; identical "
                        "payload bytes — analysis/overlap.py)")
    p.add_argument("--rs_ag_chunks", type=int, default=d.rs_ag_chunks,
                   metavar="K",
                   help="split the ZeRO-1 reduce-scatter/all-gather pair "
                        "into K pipelined column-group collectives "
                        "(payload-identical schedule knob; needs "
                        "--shard_weight_update)")
    p.add_argument("--tune_report", type=str, default=d.tune_report,
                   metavar="PATH",
                   help="tune_report.json from `make tune-overlap`: apply "
                        "the tuner's chosen schedule knobs for this "
                        "config's family (explicitly-set knob flags win)")
    p.add_argument("--no_sync_bn", dest="sync_bn", action="store_false",
                   help="per-replica BatchNorm statistics (SyncBN off)")
    p.add_argument("--no_nan_guard", dest="nan_guard", action="store_false")
    p.add_argument("--auto_recover", type=int, default=d.auto_recover,
                   metavar="N",
                   help="on divergence (NaN guard), reload the last "
                        "checkpoint and retry with the LR schedule scaled "
                        "by --recover_lr_factor, up to N times — a bare "
                        "retry would diverge identically (deterministic "
                        "epoch-seeded data order)")
    p.add_argument("--recover_lr_factor", type=float, default=d.recover_lr_factor)
    p.add_argument("--dataset", type=str, default=d.dataset,
                   help="cifar100 | cifar10 | synthetic")
    p.add_argument("--data_dir", type=str, default=d.data_dir)
    p.add_argument("--synthetic_n", type=int, default=d.synthetic_n,
                   help="synthetic train-set size")
    p.add_argument("--model", type=str, default=d.model,
                   help="resnet18/34/50, resnet50_imagenet, vit_b16/s16/tiny, "
                        "vit_moe_tiny, vit_pp_tiny, or a register_model name")
    p.add_argument("--num_classes", type=int, default=d.num_classes)
    p.add_argument("--num_processes", type=int, default=None,
                   help="multi-host world size (one process per host)")
    p.add_argument("--process_id", type=int, default=None)
    p.add_argument("--sp", type=int, default=d.sp,
                   help="sequence-parallel ways (ring attention; ViT)")
    p.add_argument("--sp_mode", choices=("ring", "ulysses"), default=d.sp_mode,
                   help="sequence-parallel strategy: 'ring' (ppermute K/V "
                        "rotation) or 'ulysses' (all_to_all tokens<->heads; "
                        "composes with --flash_attention)")
    p.add_argument("--tp", type=int, default=d.tp,
                   help="tensor-parallel ways (Megatron; ViT); composes with --sp")
    p.add_argument("--ep", type=int, default=d.ep,
                   help="expert-parallel ways (MoE ViT)")
    p.add_argument("--moe_top_k", type=int, default=d.moe_top_k,
                   help="experts per token for MoE models (1 = Switch, "
                        "2 = GShard-style renormalized gates)")
    p.add_argument("--moe_aux_coef", type=float, default=d.moe_aux_coef,
                   help="coefficient of the MoE router load-balancing loss "
                        "(Switch Transformer aux loss); 0 disables")
    p.add_argument("--pp", type=int, default=d.pp,
                   help="pipeline stages (staged ViT)")
    p.add_argument("--pp_microbatches", type=int, default=d.pp_microbatches,
                   help="pipeline microbatches; 0 = one per stage")
    p.add_argument("--pp_interleave", type=int, default=d.pp_interleave,
                   help="virtual pipeline stages per device (interleaved "
                        "schedule; v-fold bubble reduction)")
    p.add_argument("--ckpt_dir", type=str, default=None)
    p.add_argument("--keep_last_ckpts", type=int, default=None)
    p.add_argument("--resume", action="store_true")
    p.add_argument("--async_ckpt", action="store_true",
                   help="write checkpoints on a background thread (training "
                        "continues during the serialization); composes with "
                        "--sharded_ckpt as snapshot-then-write: the step loop "
                        "blocks only for the device→host snapshot, the "
                        "background writer owns serialize+CRC+commit")
    p.add_argument("--sharded_ckpt", action="store_true",
                   help="sharded checkpoint format: every process writes only "
                        "its own shard slices + a rank-0 manifest (commit "
                        "marker) — no allgather at save time, the FSDP/ZeRO-"
                        "scale choice; add --async_ckpt to move everything "
                        "but the snapshot off the step loop")
    p.add_argument("--ckpt_drain_timeout_s", type=float,
                   default=d.ckpt_drain_timeout_s, metavar="S",
                   help="bounded drain of in-flight async checkpoint writes "
                        "at fit end/interrupt; on expiry they are abandoned "
                        "LOUDLY (counted as ckpt.drain_abandoned) — <=0 "
                        "waits forever")
    p.add_argument("--ckpt_verify", dest="ckpt_verify", action="store_true",
                   default=d.ckpt_verify,
                   help="verify per-entry CRC32 stamps at restore and fall "
                        "back newest→oldest past corrupt checkpoints "
                        "(quarantined to *.corrupt) — the default")
    p.add_argument("--no_ckpt_verify", dest="ckpt_verify", action="store_false",
                   help="restore the newest checkpoint unverified (a corrupt "
                        "file still falls back, but silent bit-flips pass)")
    p.add_argument("--ckpt_io_retries", type=int, default=d.ckpt_io_retries,
                   metavar="N",
                   help="retry transient checkpoint-write failures "
                        "(OSError/EIO/ENOSPC-style) up to N times with "
                        "deterministic exponential backoff; 0 disables")
    p.add_argument("--fault_plan", type=str, default=d.fault_plan,
                   help="deterministic fault-injection plan for chaos "
                        "testing, e.g. 'ckpt_write@call=1:times=2;"
                        "sigterm@epoch=1:step=5' (docs/resilience.md; env "
                        "TPU_DIST_FAULT_PLAN when the flag is unset)")
    p.add_argument("--log_file", type=str, default=None,
                   help="JSONL metrics history path (rank 0)")
    p.add_argument("--tensorboard_dir", type=str, default=None,
                   help="TensorBoard event-file dir (self-contained writer, "
                        "no TF dependency; the reference's utils/config.py:8 "
                        "knob made functional)")
    p.add_argument("--trace_file", type=str, default=None,
                   help="write host-span Chrome trace-event JSON here at "
                        "the end of the run (Perfetto / chrome://tracing "
                        "loadable; rank 0 — docs/observability.md)")
    p.add_argument("--heartbeat_file", type=str, default=None,
                   help="per-process liveness file rewritten at the step "
                        "grain (rank 0 the bare path, rank k .h<k>; "
                        "monotonic beat counter + epoch/step position), "
                        "swept on clean exit — lets an external watchdog "
                        "tell a hung step from a slow one")
    p.add_argument("--straggler_threshold", type=float,
                   default=d.straggler_threshold, metavar="X",
                   help="warn (rank 0) + log a history record when the "
                        "slowest process's epoch time exceeds X times the "
                        "median across processes (allgathered at epoch "
                        "end); 0 disables")
    p.add_argument("--device_metrics", action="store_true",
                   help="compute in-step training-health scalars (global "
                        "grad norm, param norm, update ratio, nonfinite-"
                        "leaf count) inside the traced step, post-pmean — "
                        "zero extra collectives and zero extra per-step "
                        "fetches (TD107 contract; docs/observability.md). "
                        "Replicated-param paths only")
    p.add_argument("--anomaly_action", choices=("off", "warn", "snapshot"),
                   default=d.anomaly_action,
                   help="response to a rolling-window loss-spike/grad-norm "
                        "anomaly: 'warn' (default) logs a rank-0 warning + "
                        "history record; 'snapshot' additionally writes an "
                        "exact mid-epoch checkpoint (the emergency-snapshot "
                        "discipline) before the run can diverge further; "
                        "'off' disables detection")
    p.add_argument("--anomaly_window", type=int, default=d.anomaly_window,
                   metavar="N",
                   help="rolling-median window of the anomaly detector, in "
                        "observations at the --log_every cadence")
    p.add_argument("--anomaly_loss_spike", type=float,
                   default=d.anomaly_loss_spike, metavar="X",
                   help="flag a loss above X times the rolling median")
    p.add_argument("--anomaly_grad_spike", type=float,
                   default=d.anomaly_grad_spike, metavar="X",
                   help="flag a grad norm above X times the rolling median "
                        "(grad norms need --device_metrics)")
    p.add_argument("--profile_dir", type=str, default=None,
                   help="XLA profile output dir: alone, captures epoch 0 "
                        "(TensorBoard profile tab); with --profile_trigger/"
                        "--profile_steps, holds their bounded capture "
                        "windows instead")
    p.add_argument("--metrics_file", type=str, default=None,
                   help="live OpenMetrics/Prometheus textfile (node-"
                        "exporter textfile-collector format): counters, "
                        "epoch rollup, goodput and alert gauges, written "
                        "atomically at the heartbeat's step-grain throttle "
                        "(rank 0 the bare path, rank k .h<k> — "
                        "docs/observability.md)")
    p.add_argument("--metrics_port", type=int, default=d.metrics_port,
                   help="serve the same exposition on a rank-0-only "
                        "background HTTP /metrics endpoint (stdlib, "
                        "serves the last snapshot — a scrape can never "
                        "stall a step); 0 disables")
    p.add_argument("--alert_rules", type=str, default=None,
                   help="declarative threshold alerting: 'default' (the "
                        "built-in library: stall/MFU/goodput/grad-norm/"
                        "heartbeat/retrace rules) or a TOML/JSON spec "
                        "path (metric, comparator, threshold, sustain-"
                        "for-N-windows, cooldown). Fired rules emit "
                        "'alert' history records, rank-0 warnings, and "
                        "alert_active exporter gauges; rules with "
                        "profile=true arm the triggered profiler")
    p.add_argument("--crash_dir", type=str, default=None,
                   help="crash-forensics directory: every rank writes a "
                        "SIGKILL-surviving flight-recorder ring "
                        "(fixed-slot atomic writes — step boundaries, "
                        "span opens, ckpt/alert/anomaly/resume events, "
                        "counter deltas, a fatal slot from the excepthook "
                        "wrappers) plus a faulthandler stack-dump file "
                        "(hard faults; SIGUSR1 dumps all threads on "
                        "demand, the launcher watchdog's stack-capture "
                        "channel). Assemble with `python -m tpu_dist.obs "
                        "postmortem <dir>` (docs/observability.md)")
    p.add_argument("--memory_check", type=str, default=d.memory_check,
                   choices=("off", "warn", "refuse"),
                   help="pre-flight HBM feasibility lint: price the "
                        "static per-leaf memory ledger (params/opt-state/"
                        "EF/BN/batch, sharded extents) against the "
                        "per-chip HBM budget BEFORE the first compile; "
                        "'refuse' stops an infeasible config, 'warn' "
                        "prints (docs/observability.md)")
    p.add_argument("--memory_headroom", type=float,
                   default=d.memory_headroom, metavar="FRAC",
                   help="fraction of the per-chip HBM budget the static "
                        "estimate may claim (rest reserved for XLA "
                        "temps/workspace)")
    p.add_argument("--hbm_budget_bytes", type=int, default=None,
                   help="per-device HBM budget override in bytes "
                        "(default: the chip table — "
                        "obs/costmodel.CHIP_HBM_BYTES)")
    p.add_argument("--auto_shard", choices=("off", "plan", "apply"),
                   default=d.auto_shard,
                   help="static sharding planner at startup "
                        "(analysis/planner.py): enumerate the shardlint "
                        "family matrix, price each candidate with the "
                        "calibrated cost model + HLO wire bytes, refuse "
                        "HBM-infeasible ones through the --memory_check "
                        "path, and print the ranked plan (also lands in "
                        "the history as a 'plan' record, TD119-gated). "
                        "'apply' rewrites this config to the winning "
                        "family's flags before training (docs/planner.md)")
    p.add_argument("--per_host_log", action="store_true",
                   help="every process writes its own JSONL history "
                        "(<log_file>.h<rank>; rank 0 keeps the bare path) "
                        "so `python -m tpu_dist.obs pod` can merge the "
                        "cross-host view (docs/observability.md)")
    p.add_argument("--profile_trigger", type=str, default=d.profile_trigger,
                   help="arm a bounded on-device profiler capture when a "
                        "health signal fires: 'auto' (all), or a comma "
                        "list of anomaly,straggler,retrace; 'off' (the "
                        "default) disables. Anomaly/retrace captures run "
                        "on rank 0; straggler captures on the flagged "
                        "host. Needs --profile_dir; bounded by "
                        "--profile_window/cooldown/max_captures")
    p.add_argument("--profile_steps", type=str, default=None, metavar="A:B",
                   help="manually capture global steps [A, B) to "
                        "--profile_dir (replaces the epoch-0 blanket "
                        "trace that --profile_dir alone takes)")
    p.add_argument("--profile_window", type=int, default=d.profile_window,
                   help="steps per triggered profiler capture")
    p.add_argument("--profile_cooldown", type=int,
                   default=d.profile_cooldown,
                   help="minimum steps between triggered captures")
    p.add_argument("--profile_max_captures", type=int,
                   default=d.profile_max_captures,
                   help="cap on triggered captures per process (an anomaly "
                        "storm must not trace the whole run)")
    p.add_argument("--eval_every", type=int, default=d.eval_every,
                   help="epochs between evaluations; 0 disables")
    p.add_argument("--save_every", type=int, default=d.save_every)
    p.add_argument("--mid_epoch_save_every", type=int,
                   default=d.mid_epoch_save_every,
                   help="periodic exact mid-epoch snapshots every N steps "
                        "(0 = off); resume continues at the exact batch — "
                        "kill-9 safety for long epochs")
    p.add_argument("--steps_per_epoch", type=int, default=None,
                   help="cap steps per epoch (smokes/benches)")
    p.add_argument("--log_every", type=int, default=d.log_every)
    p.add_argument("--compile_cache_dir", type=str, default=None,
                   help="persistent XLA compile-cache dir (repeat runs skip "
                        "the cold first compile)")
    # accepted for command-line parity with torch.distributed.launch; unused
    p.add_argument("--local_rank", type=int, default=0, help=argparse.SUPPRESS)
    p.add_argument("--gpu", type=str, default=None, help=argparse.SUPPRESS)
    # BASELINE.json north star names the switch `--backend=xla`: accept it.
    # 'xla' is the only backend this framework has (collectives ride
    # ICI/DCN through XLA); asking for nccl/gloo gets a pointed refusal
    # rather than a silent ignore.
    p.add_argument(
        "--backend", choices=("xla", "nccl", "gloo", "mpi"), default="xla",
        help="distributed backend; this framework is TPU-native, so 'xla' "
             "is the only real choice (reference: init_process_group "
             "backend, distributed.py:49)",
    )
    return p


def config_from_args(args: argparse.Namespace, **overrides) -> TrainConfig:
    backend = getattr(args, "backend", "xla")
    if backend != "xla":
        raise SystemExit(
            f"--backend {backend} is the reference's CUDA-world choice; this "
            "framework runs XLA collectives over ICI/DCN and has no "
            f"{backend} path — use --backend xla (the default)"
        )
    fields = {f.name for f in dataclasses.fields(TrainConfig)}
    kw = {k: v for k, v in vars(args).items() if k in fields}
    if "lr_milestones" in kw:  # argparse nargs gives a list; config is a tuple
        kw["lr_milestones"] = tuple(kw["lr_milestones"])
    kw.update(overrides)
    return TrainConfig(**kw)
