from tpu_dist.config.config import TrainConfig, add_reference_flags, config_from_args  # noqa: F401
