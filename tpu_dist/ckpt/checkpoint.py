"""Process-0 checkpoint / resume.

Makes real what the reference only documents: the rank-0-guarded model save
(``tutorials/2:§7``), the dead ``save_epoch`` knob (``utils/config.py:7``)
and the reserved ``/ckpts`` directory (``.gitignore:4``). Saves the whole
:class:`TrainState` (params, BN stats, momentum buffers, step) plus the
epoch — enough for exact resume.

Format: one ``.npz`` of flattened arrays keyed by pytree path + a JSON
sidecar with the epoch and keys. Atomic via write-to-temp + rename. Only
process 0 writes (single-writer discipline); every process can read.
"""

from __future__ import annotations

import json
import os
import re
import time
import zipfile
import zlib
from typing import Any, List, Optional, Tuple

import jax
import numpy as np

from tpu_dist.elastic.errors import ConfigMismatchError, ElasticShapeMismatch
from tpu_dist.obs import counters, spans
from tpu_dist.resilience import faults
from tpu_dist.resilience import retry as retry_lib
from tpu_dist.train.state import TrainState

_CKPT_RE = re.compile(r"ckpt_(\d+)\.npz$")


class CheckpointCorruptError(RuntimeError):
    """A checkpoint file failed integrity verification (torn write, CRC
    mismatch, unreadable archive). The restore ladder quarantines the file
    and falls back to the next older checkpoint (docs/resilience.md)."""


#: Exceptions a *read* of a damaged checkpoint can raise below the
#: integrity layer — the restore ladder treats these like a CRC failure.
#: (Deliberately excludes ValueError: shape/layout mismatches are config
#: errors that must raise, not quarantine.)
CKPT_READ_ERRORS = (
    OSError,
    EOFError,
    zlib.error,
    zipfile.BadZipFile,
    json.JSONDecodeError,
)

# Transient-write retry count for every checkpoint file write in this
# module (process-global, like the compile-cache jax.config state — the
# Trainer sets it from --ckpt_io_retries). Delays are deterministic
# exponential backoff (resilience/retry.py).
_IO_RETRIES = 0


def set_io_retries(n: int) -> int:
    """Set the module-wide transient-write retry count; returns the
    previous value."""
    global _IO_RETRIES
    prev, _IO_RETRIES = _IO_RETRIES, max(0, int(n))
    return prev


def _entry_crc(arr: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(arr).tobytes()) & 0xFFFFFFFF


def _scalar_to_host(x):
    """Host value of a (possibly process-spanning, replicated) scalar leaf:
    the local addressable shard holds it — no collective, no device_get on
    a global array (which raises across processes)."""
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        return np.asarray(x.addressable_shards[0].data)
    return np.asarray(jax.device_get(x))


def _leaf_to_host(leaf) -> np.ndarray:
    """Bring one leaf fully to host. Leaves sharded across processes (ZeRO-1
    opt state under P('data'), TP-sharded params on a multi-host mesh) are
    not addressable from process 0 alone — gather them collectively first.
    NOTE: collective ⇒ every process must reach this call (see save())."""
    if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
        from jax.experimental import multihost_utils  # noqa: PLC0415

        return np.asarray(multihost_utils.process_allgather(leaf, tiled=True))
    return np.asarray(jax.device_get(leaf))


def _flatten(tree) -> dict:
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = jax.tree_util.keystr(path)
        flat[key] = _leaf_to_host(leaf)
    return flat


_EF_KEY_PREFIX = "['ef']"  # TrainState.ef subtree in keystr form


def _missing_ok(key: str, leaf) -> Optional[np.ndarray]:
    """Zeros for a template leaf the checkpoint may legitimately lack:
    enabling ``int8_ef`` on a checkpoint written without residuals — zero
    residuals ARE the correct cold start (error feedback warms up in one
    step). Returns None for every other key (hard error upstream)."""
    if key.startswith(_EF_KEY_PREFIX):
        return np.zeros(np.shape(leaf), getattr(leaf, "dtype", np.float32))
    return None


def _resolve_shape_mismatch(remap, key: str, arr: np.ndarray, leaf, template):
    """A checkpoint entry's shape disagrees with the template: apply the
    elastic ``remap`` hook (the trainer's restore ladder always supplies
    one — docs/resilience.md "Elastic training"), or raise the typed
    error: :class:`ElasticShapeMismatch` for a dp-extent-dependent leaf
    saved at a different world size (benign — retry with a remapper),
    :class:`ConfigMismatchError` for everything else (real config drift,
    which must never be silently resumed past)."""
    if remap is not None:
        out = remap(key, arr, leaf)
        if out is not None:
            if tuple(np.shape(out)) != tuple(np.shape(leaf)):
                raise ConfigMismatchError(
                    f"elastic remap of {key} produced shape "
                    f"{tuple(np.shape(out))}, template wants "
                    f"{tuple(np.shape(leaf))} — remapper/template "
                    "disagreement"
                )
            return out
    from tpu_dist.elastic.remap import classify, params_len  # noqa: PLC0415

    L = params_len(template.get("params", {})) if isinstance(template, dict) else 0
    want = tuple(np.shape(leaf))
    got = tuple(np.shape(arr))
    if L and classify(key, got, want, L) is not None:
        raise ElasticShapeMismatch(key, got, want)
    raise ConfigMismatchError(
        f"shape mismatch for {key}: ckpt {got} vs state {want}"
    )


def _unflatten(template, flat: dict, remap=None):
    paths_leaves, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves = []
    for path, leaf in paths_leaves:
        key = jax.tree_util.keystr(path)
        if key not in flat:
            zero = _missing_ok(key, leaf)
            if zero is not None:
                leaves.append(zero)
                continue
            raise KeyError(f"checkpoint missing array for {key}")
        arr = flat[key]
        if tuple(arr.shape) != tuple(leaf.shape):
            arr = _resolve_shape_mismatch(remap, key, arr, leaf, template)
        leaves.append(arr.astype(leaf.dtype))
    return jax.tree_util.tree_unflatten(treedef, [l for l in leaves])


def _write_npz(
    ckpt_dir: str, name: str, flat: dict, meta: dict,
    keep_last: Optional[int] = None,
) -> str:
    """Serialize + atomically publish one checkpoint file (host-side only —
    safe to run on a worker thread; ``flat`` holds host numpy copies).

    Per-entry CRC32s are stamped into ``__meta__`` so restore can verify
    integrity; transient write failures retry per :func:`set_io_retries`
    (atomic tmp+rename makes an attempt idempotent)."""
    os.makedirs(ckpt_dir, exist_ok=True)
    flat = dict(flat)
    meta = dict(meta)
    meta["crc32"] = {k: _entry_crc(v) for k, v in flat.items()}
    flat["__meta__"] = np.frombuffer(json.dumps(meta).encode(), dtype=np.uint8)
    path = os.path.join(ckpt_dir, name)
    tmp = path + ".tmp"

    def attempt() -> None:
        faults.on_ckpt_write()  # no-op unless a --fault_plan clause is armed
        # tpu-dist: ignore[TD002] — every caller holds the rank-0 guard (the
        # guard can't live here: callers flatten collectively before it)
        with open(tmp, "wb") as f:
            np.savez(f, **flat)
        os.replace(tmp, path)  # atomic: a ckpt is either absent or complete

    with spans.span("ckpt/write", file=name):
        retry_lib.retry_call(
            attempt, retries=_IO_RETRIES, describe=f"write of {name}"
        )
    counters.inc("ckpt.writes")
    try:
        counters.inc("ckpt.bytes_written", os.path.getsize(path))
    except OSError:  # tpu-dist: ignore[TD006] — telemetry only: a racing
        pass  # prune/corruption-injection must not fail the publish
    faults.on_ckpt_published(path)  # --fault_plan ckpt_corrupt hook (no-op off)
    if keep_last is not None and keep_last > 0:
        with spans.span("ckpt/prune", keep_last=keep_last):
            sweep_stale_tmp(ckpt_dir)  # crash-leaked *.tmp never accumulates
            epochs = sorted(
                int(m.group(1))
                for m in (_CKPT_RE.search(n) for n in os.listdir(ckpt_dir))
                if m
            )
            for e in epochs[:-keep_last]:
                try:
                    os.remove(os.path.join(ckpt_dir, f"ckpt_{e}.npz"))
                    counters.inc("ckpt.pruned")
                except OSError:  # tpu-dist: ignore[TD006] — prune is best-effort:
                    pass  # a file already gone (or unlinkable) must not fail a save
    return path


def sweep_stale_tmp(ckpt_dir: str) -> List[str]:
    """Remove checkpoint temp files leaked by a crash between ``open(tmp)``
    and ``os.replace`` (``*.npz.tmp`` / ``*.manifest.json.tmp``). Safe only
    under the single-writer discipline: call from the writing process with
    no write in flight (the prune path and resume startup both qualify).
    Returns the removed names."""
    removed: List[str] = []
    try:
        names = os.listdir(ckpt_dir)
    except OSError:
        return removed
    for n in names:
        if n.endswith(".npz.tmp") or n.endswith(".manifest.json.tmp"):
            try:
                os.remove(os.path.join(ckpt_dir, n))
                removed.append(n)
            except OSError:  # tpu-dist: ignore[TD006] — best-effort sweep
                pass
    return removed


def save(
    ckpt_dir: str,
    state: TrainState,
    epoch: int,
    keep_last: Optional[int] = None,
    extra_meta: Optional[dict] = None,
    name: Optional[str] = None,
) -> Optional[str]:
    """Write ``ckpt_{epoch}.npz``; no-op off process 0 (rank-0 guard).

    ``keep_last``: prune to the N newest checkpoints after writing.
    ``extra_meta``: extra JSON-serializable keys for the sidecar (e.g. the
    pipeline layout tag — interleaved storage permutes block order, so a
    resume under a different ``pp_interleave`` must be refused, not run
    silently wrong).
    ``name`` overrides the file name — an off-namespace name (one the
    ``ckpt_{N}.npz`` discovery regex cannot match, e.g. the trainer's
    ``anomaly_*`` forensic snapshots) is never auto-resumed, never
    pruned, and never overwritten by the periodic saves."""
    # flatten BEFORE the rank-0 guard: gathering cross-process-sharded
    # leaves is collective, so every process must participate
    flat = _flatten(state._asdict())
    if jax.process_index() != 0:
        return None
    meta = {"epoch": epoch, "step": int(_scalar_to_host(state.step))}
    if extra_meta:
        meta.update(extra_meta)
    return _write_npz(
        ckpt_dir, name or f"ckpt_{epoch}.npz", flat, meta, keep_last
    )


def save_best(
    ckpt_dir: str,
    state: TrainState,
    epoch: int,
    metric: float,
    extra_meta: Optional[dict] = None,
) -> Optional[str]:
    """Write/overwrite ``ckpt_best.npz`` (rank-0, atomic) tagging the metric."""
    flat = _flatten(state._asdict())  # collective: before the rank-0 guard
    if jax.process_index() != 0:
        return None
    meta = {"epoch": epoch, "metric": metric}
    if extra_meta:
        meta.update(extra_meta)
    return _write_npz(ckpt_dir, "ckpt_best.npz", flat, meta)


class _AsyncWriter:
    """Single-worker background publisher shared by the async writers
    (:class:`AsyncCheckpointer`, :class:`AsyncShardedCheckpointer`).

    Publish order is the submission order (one worker thread). A save
    never blocks on an earlier write still in flight — it only harvests
    ALREADY-finished writes to surface their errors; ``wait()`` blocks on
    everything outstanding and re-raises the first writer error. Call
    ``wait()`` (or ``close()``, which also releases the worker thread)
    before process exit — the Trainer does, at the end of ``fit()`` and in
    the interrupt path.

    ``wait``/``close`` take an optional ``timeout`` (seconds) and return
    False when it expires with writes still in flight — the bounded-drain
    contract the Trainer's ``_ckpt_close`` builds its loud
    refusal-to-lose-data path on. A timed-out ``close`` cancels writes
    that have not STARTED (their data is lost and the caller must say so);
    the write already on the worker thread keeps running to completion so
    a half-written file is never abandoned mid-publish (atomic tmp+rename
    makes even that crash-safe).
    """

    def __init__(self) -> None:
        from concurrent.futures import ThreadPoolExecutor  # noqa: PLC0415

        self._pool = ThreadPoolExecutor(max_workers=1, thread_name_prefix="ckpt")
        self._pending: list = []

    @property
    def in_flight(self) -> int:
        """Writes submitted but not yet finished (snapshot data whose loss
        a timed-out drain must report)."""
        return sum(1 for f in self._pending if not f.done())

    def _harvest(self, block: bool, deadline: Optional[float] = None) -> bool:
        import concurrent.futures as _cf  # noqa: PLC0415

        first_err = None
        drained = True
        while self._pending and (block or self._pending[0].done()):
            fut = self._pending[0]
            try:
                if deadline is None:
                    fut.result()
                else:
                    fut.result(max(0.0, deadline - time.monotonic()))
            except _cf.TimeoutError:
                if not fut.done():  # drain timeout, not the write's own error
                    drained = False
                    break
                if first_err is None:  # the WRITE raised a TimeoutError
                    first_err = fut.exception()
            except Exception as e:  # keep draining; re-raise the first
                if first_err is None:
                    first_err = e
            self._pending.pop(0)
        if first_err is not None:
            raise first_err
        return drained

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until every outstanding write is published (re-raising the
        first writer-thread exception here), or until ``timeout`` seconds
        elapse — returns False iff the timeout expired with writes still
        in flight."""
        deadline = None if timeout is None else time.monotonic() + timeout
        return self._harvest(block=True, deadline=deadline)

    def close(self, timeout: Optional[float] = None) -> bool:
        """``wait(timeout)`` then release the worker thread; the instance
        is dead afterwards (a new save would raise from the shut-down
        pool). Returns False iff the bounded drain gave up — not-yet-
        started writes are cancelled and the caller owns reporting the
        loss (``in_flight`` still counts them)."""
        try:
            drained = self.wait(timeout)
        except Exception:
            self._pool.shutdown(wait=True)
            raise
        if drained:
            self._pool.shutdown(wait=True)
        else:
            self._pool.shutdown(wait=False, cancel_futures=True)
        return drained


class AsyncCheckpointer(_AsyncWriter):
    """Overlap checkpoint WRITES with training (the orbax-style async-save
    pattern, self-contained).

    The device→host snapshot (``_flatten``) stays synchronous — it is the
    data dependency on the live ``TrainState`` and, multi-host, a
    collective every process must join. The expensive part (npz
    serialization + atomic rename + pruning) runs on a single worker
    thread over the host copies, so the train loop resumes immediately.
    Drain semantics live in :class:`_AsyncWriter`.
    """

    def save(
        self,
        ckpt_dir: str,
        state: TrainState,
        epoch: int,
        keep_last: Optional[int] = None,
        extra_meta: Optional[dict] = None,
    ) -> Optional[str]:
        """Snapshot synchronously, write in the background; returns the
        EVENTUAL path. The file exists only after the background write
        publishes — call :meth:`wait` (or :meth:`close`) before reading
        the path or relying on it surviving a crash; write errors surface
        on the next save/wait/close, not here. The Trainer drains via
        ``wait()`` at epoch boundaries and ``close()`` on exit."""
        flat = _flatten(state._asdict())  # sync: collective + host snapshot
        if jax.process_index() != 0:
            return None
        self._harvest(block=False)  # surface finished writes' errors only
        meta = {"epoch": epoch, "step": int(_scalar_to_host(state.step))}
        if extra_meta:
            meta.update(extra_meta)
        self._pending.append(self._pool.submit(
            _write_npz, ckpt_dir, f"ckpt_{epoch}.npz", flat, meta, keep_last
        ))
        return os.path.join(ckpt_dir, f"ckpt_{epoch}.npz")

    def save_best(
        self,
        ckpt_dir: str,
        state: TrainState,
        epoch: int,
        metric: float,
        extra_meta: Optional[dict] = None,
    ) -> Optional[str]:
        """Best-model twin of :meth:`save` — same EVENTUAL-path contract:
        the returned path is valid only after :meth:`wait`/:meth:`close`."""
        flat = _flatten(state._asdict())
        if jax.process_index() != 0:
            return None
        self._harvest(block=False)
        meta = {"epoch": epoch, "metric": metric}
        if extra_meta:
            meta.update(extra_meta)
        self._pending.append(self._pool.submit(
            _write_npz, ckpt_dir, "ckpt_best.npz", flat, meta
        ))
        return os.path.join(ckpt_dir, "ckpt_best.npz")


def all_checkpoints(ckpt_dir: str) -> List[Tuple[str, int]]:
    """Every epoch checkpoint in ``ckpt_dir``, newest first — the restore
    ladder's walk order. ``*.tmp`` (torn) and ``*.corrupt`` (quarantined)
    files never appear (the name regex is anchored on ``.npz``)."""
    if not os.path.isdir(ckpt_dir):
        return []
    found = []
    for name in os.listdir(ckpt_dir):
        m = _CKPT_RE.search(name)
        if m:
            found.append((os.path.join(ckpt_dir, name), int(m.group(1))))
    return sorted(found, key=lambda pe: pe[1], reverse=True)


def latest_checkpoint(ckpt_dir: str) -> Optional[Tuple[str, int]]:
    """Returns ``(path, epoch)`` of the newest complete checkpoint."""
    ladder = all_checkpoints(ckpt_dir)
    return ladder[0] if ladder else None


def quarantine(path: str) -> str:
    """Move a corrupt/unreadable checkpoint file out of the resume path by
    renaming it to ``*.corrupt`` (uniquified). The file is kept for
    forensics — prune sweeps skip quarantined names — but no discovery
    function will ever report it as a checkpoint again."""
    dst = path + ".corrupt"
    i = 1
    while os.path.exists(dst):
        dst = f"{path}.corrupt.{i}"
        i += 1
    os.replace(path, dst)
    counters.inc("ckpt.quarantines")
    return dst


def verify_npz(path: str) -> dict:
    """Integrity-check one plain checkpoint: the archive must be readable
    end to end and every entry must match its CRC32 stamp in ``__meta__``
    (checkpoints written before stamping existed get the structural check
    only). Returns the meta dict; raises :class:`CheckpointCorruptError`."""
    try:
        with np.load(path) as z:
            meta = {}
            if "__meta__" in z.files:
                meta = json.loads(bytes(z["__meta__"].tobytes()).decode())
            crcs = meta.get("crc32")
            if crcs is not None:
                missing = set(crcs) - set(z.files)
                if missing:
                    raise CheckpointCorruptError(
                        f"{path}: stamped entries missing from archive: "
                        f"{sorted(missing)[:4]}"
                    )
            for k in z.files:
                if k == "__meta__":
                    continue
                arr = z[k]  # full decompress: zip-level CRC checked here
                if crcs is not None:
                    want = crcs.get(k)
                    if want is None:
                        raise CheckpointCorruptError(
                            f"{path}: entry {k!r} has no CRC stamp"
                        )
                    if _entry_crc(arr) != int(want) & 0xFFFFFFFF:
                        raise CheckpointCorruptError(
                            f"{path}: CRC32 mismatch on entry {k!r} — "
                            "silent corruption"
                        )
    except CheckpointCorruptError:
        raise
    except Exception as e:  # BadZipFile / zlib.error / OSError / EOF / json
        raise CheckpointCorruptError(
            f"unreadable checkpoint {path}: {type(e).__name__}: {e}"
        ) from e
    return meta


def read_meta(path: str) -> dict:
    """The JSON sidecar of a checkpoint (epoch, step, any extra_meta)."""
    with np.load(path) as z:
        if "__meta__" not in z.files:
            return {}
        return json.loads(bytes(z["__meta__"].tobytes()).decode())


def restore(
    path: str, template: TrainState, verify: bool = False, remap=None
) -> TrainState:
    """Rebuild a TrainState shaped like ``template`` from ``path``.

    Arrays come back as host numpy; the caller re-places them on the mesh
    (the trainer does this when resuming). ``verify=True`` CRC-checks each
    entry against its ``__meta__`` stamp AS IT IS READ — same coverage as
    :func:`verify_npz` in the single decompression pass the restore does
    anyway (a separate verify-then-restore would read the archive twice).
    ``remap`` is the elastic shape-mismatch hook (``tpu_dist/elastic/
    remap.py``): entries whose shape bakes in a different data-parallel
    extent are rebuilt for this run's extent instead of raising — without
    it, such entries raise the typed :class:`ElasticShapeMismatch`.
    """
    with spans.span("ckpt/restore", file=os.path.basename(path)), np.load(path) as z:
        crcs = None
        if verify:
            meta = {}
            if "__meta__" in z.files:
                meta = json.loads(bytes(z["__meta__"].tobytes()).decode())
            crcs = meta.get("crc32")
            if crcs is not None:
                missing = set(crcs) - set(z.files)
                if missing:
                    raise CheckpointCorruptError(
                        f"{path}: stamped entries missing from archive: "
                        f"{sorted(missing)[:4]}"
                    )
        flat = {}
        for k in z.files:
            if k == "__meta__":
                continue
            arr = z[k]
            if crcs is not None:
                want = crcs.get(k)
                if want is None:
                    raise CheckpointCorruptError(
                        f"{path}: entry {k!r} has no CRC stamp"
                    )
                if _entry_crc(arr) != int(want) & 0xFFFFFFFF:
                    raise CheckpointCorruptError(
                        f"{path}: CRC32 mismatch on entry {k!r} — silent "
                        "corruption"
                    )
            flat[k] = arr
    d: Any = _unflatten(template._asdict(), flat, remap=remap)
    return TrainState(**d)


# ---------------------------------------------------------------------------
# Sharded checkpointing (FSDP/ZeRO-3 scale): NO gather at save time, and
# NO full-model host copy at restore time.
#
# The plain ``save()`` allgathers cross-process-sharded leaves to process 0
# — correct, but at ZeRO-3 scale it recreates on one host exactly the full
# copy the sharding exists to avoid (network + rank-0 host memory ∝ total
# params). The sharded format instead has EVERY process write only the
# shard slices it already holds:
#
#   {stem}.shard{p}of{n}.npz   one per process; keys are
#                              "{leaf}|{starts}|{sizes}" — the slice origin
#                              AND extent in the global array, so restore
#                              can decide overlap from the zip directory
#                              alone, without decompressing pieces.
#   {stem}.manifest.json       rank-0-written LAST — the commit marker
#                              (epoch/meta/global shapes/expected shard-
#                              file count); a checkpoint without its
#                              manifest is incomplete and invisible.
#
# Overwriting an existing stem (ckpt_best) UNCOMMITS first: rank 0 deletes
# the old manifest, a barrier guarantees no process touches a shard file
# while a stale manifest could still point at a mixed set, then shards are
# replaced and the new manifest commits.
#
# Restore is overlap-only: each process reads the zip directories of all n
# shard files (cheap), then decompresses ONLY the pieces intersecting the
# shards its own target sharding assigns it, pasting into per-shard host
# buffers and assembling device arrays via
# ``jax.make_array_from_single_device_arrays`` — per-process restore
# memory ∝ its own partition (plus one full copy of any REPLICATED leaf,
# which every device holds anyway). The torch-distributed-checkpoint /
# orbax-sharded role, in the same self-contained npz idiom as the rest of
# this module.
# ---------------------------------------------------------------------------

_MANIFEST_RE = re.compile(r"ckpt_(\d+)\.manifest\.json$")
_NUMERIC_CKPT_FILE_RE = re.compile(r"ckpt_(\d+)\.(?:shard|manifest)")


def _shard_key(key: str, index, shape) -> str:
    starts = ",".join(str(sl.start or 0) for sl in index)
    sizes = ",".join(str(d) for d in shape)
    return f"{key}|{starts}|{sizes}"


def _parse_shard_key(skey: str):
    key, starts, sizes = skey.rsplit("|", 2)
    origin = tuple(int(s) for s in starts.split(",")) if starts else ()
    extent = tuple(int(s) for s in sizes.split(",")) if sizes else ()
    return key, origin, extent


class ShardSnapshot:
    """Phase-1 product of the two-phase sharded save: this process's shard
    slices as host numpy copies, plus everything phase 2 (serialize + CRC +
    publish + manifest commit) needs — so phase 2 can run on a background
    thread with no reference to the live ``TrainState`` (docs/
    checkpointing.md "Two-phase sharded saves")."""

    __slots__ = ("stem", "epoch", "pid", "nproc", "shard_flat", "shapes", "meta")

    def __init__(self, stem, epoch, pid, nproc, shard_flat, shapes, meta):
        self.stem = stem
        self.epoch = epoch
        self.pid = pid
        self.nproc = nproc
        self.shard_flat = shard_flat
        self.shapes = shapes
        self.meta = meta

    @property
    def nbytes(self) -> int:
        return sum(int(v.nbytes) for v in self.shard_flat.values())


def snapshot_sharded(
    state: TrainState,
    epoch: int,
    extra_meta: Optional[dict] = None,
    stem: Optional[str] = None,
) -> ShardSnapshot:
    """Phase 1 of the sharded save: device→host copies of the shard slices
    this process owns. Collective-free (unlike ``_flatten``: every slice
    read here is locally addressable) and filesystem-free — this is the
    ONLY part of a sharded save that must block the step loop."""
    stem = stem or f"ckpt_{epoch}"
    pid, nproc = jax.process_index(), jax.process_count()
    shard_flat: dict = {}
    shapes: dict = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(state._asdict())[0]:
        key = jax.tree_util.keystr(path)
        if isinstance(leaf, jax.Array):
            shapes[key] = list(leaf.shape)
            seen = set()
            for sh in leaf.addressable_shards:
                if sh.replica_id != 0:  # one writer per distinct slice
                    continue
                origin = tuple(sl.start or 0 for sl in sh.index)
                if origin in seen:
                    continue
                seen.add(origin)
                data = np.asarray(sh.data)
                shard_flat[_shard_key(key, sh.index, data.shape)] = data
        else:  # host scalars/arrays
            shapes[key] = list(np.shape(leaf))
            if pid == 0:
                data = np.asarray(leaf)
                shard_flat[_shard_key(key, (), data.shape)] = data
    meta = {"epoch": epoch, "step": int(_scalar_to_host(state.step))}
    if extra_meta:
        meta.update(extra_meta)
    return ShardSnapshot(stem, epoch, pid, nproc, shard_flat, shapes, meta)


def _sharded_uncommit(ckpt_dir: str, stem: str) -> None:
    """UNCOMMIT an existing checkpoint at this stem before any process
    replaces its shard file — a crash mid-overwrite must leave an
    (invisible) uncommitted checkpoint, never a committed mixed one.
    Collective (the barrier), so it always runs on the main thread."""
    os.makedirs(ckpt_dir, exist_ok=True)
    if jax.process_index() == 0:
        try:
            os.remove(os.path.join(ckpt_dir, f"{stem}.manifest.json"))
        except FileNotFoundError:
            pass
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils  # noqa: PLC0415

        multihost_utils.sync_global_devices(f"ckpt_uncommit_{stem}")


def _write_shard_file(ckpt_dir: str, snap: ShardSnapshot) -> str:
    """Phase 2a: serialize + CRC32-stamp + retry + atomically publish this
    process's shard file. Host-side only — safe on a worker thread."""
    # self-describing integrity: each shard carries the CRC32 of its own
    # entries (rank 0 cannot know other processes' bytes for the manifest)
    shard_flat = dict(snap.shard_flat)
    shard_flat["__crc__"] = np.frombuffer(
        json.dumps({k: _entry_crc(v) for k, v in shard_flat.items()}).encode(),
        dtype=np.uint8,
    )
    name = f"{snap.stem}.shard{snap.pid}of{snap.nproc}.npz"
    tmp = os.path.join(ckpt_dir, name + ".tmp")

    def write_shard() -> None:
        faults.on_ckpt_write()  # --fault_plan injection point (no-op off)
        # tpu-dist: ignore[TD002] — sharded format: EVERY process writes its
        # own shard piece by design; the rank-0-only commit is the manifest
        with open(tmp, "wb") as f:
            np.savez(f, **shard_flat)
        os.replace(tmp, os.path.join(ckpt_dir, name))

    with spans.span("ckpt/write_shard", file=name):
        retry_lib.retry_call(
            write_shard, retries=_IO_RETRIES, describe=f"write of {name}"
        )
    counters.inc("ckpt.writes")
    try:
        counters.inc(
            "ckpt.bytes_written", os.path.getsize(os.path.join(ckpt_dir, name))
        )
    except OSError:  # tpu-dist: ignore[TD006] — telemetry only (see _write_npz)
        pass
    return os.path.join(ckpt_dir, name)


def _await_shard_files(
    ckpt_dir: str, snap: ShardSnapshot, timeout_s: float
) -> None:
    """Filesystem commit barrier for the BACKGROUND publish path: rank 0's
    writer thread must not commit the manifest until every process's shard
    file is published. Shard files appear atomically (tmp+rename), so
    existence ⇒ complete. The synchronous path uses ``sync_global_devices``
    instead — a jax collective a background thread must never hold."""
    names = [
        f"{snap.stem}.shard{p}of{snap.nproc}.npz" for p in range(snap.nproc)
    ]
    deadline = time.monotonic() + timeout_s
    while True:
        missing = [
            n for n in names if not os.path.exists(os.path.join(ckpt_dir, n))
        ]
        if not missing:
            return
        if time.monotonic() >= deadline:
            raise RuntimeError(
                f"sharded-ckpt commit barrier: {len(missing)} of "
                f"{snap.nproc} shard files still missing after "
                f"{timeout_s:.0f}s ({missing[:3]}) — refusing to commit "
                f"manifest {snap.stem} over an incomplete shard set"
            )
        time.sleep(0.05)


def _commit_manifest(
    ckpt_dir: str, snap: ShardSnapshot, keep_last: Optional[int] = None
) -> str:
    """Phase 2b (rank 0 only): write the manifest — the commit marker —
    then prune. Host-side only; the caller guarantees all shard files are
    already published (barrier)."""
    mpath = os.path.join(ckpt_dir, f"{snap.stem}.manifest.json")
    manifest = {"meta": snap.meta, "n_shards": snap.nproc, "shapes": snap.shapes}
    tmp = mpath + ".tmp"

    def write_manifest() -> None:
        faults.on_ckpt_write()
        # tpu-dist: ignore[TD002] — callers gate on snap.pid == 0; the
        # manifest commit is rank-0-only by construction
        with open(tmp, "w") as f:
            json.dump(manifest, f)
        os.replace(tmp, mpath)

    with spans.span("ckpt/write_manifest", file=os.path.basename(mpath)):
        retry_lib.retry_call(
            write_manifest, retries=_IO_RETRIES, describe=f"commit of {snap.stem}"
        )
    counters.inc("ckpt.writes")
    faults.on_ckpt_published(mpath)
    if keep_last is not None and keep_last > 0:
        sweep_stale_tmp(ckpt_dir)  # post-commit barrier: no write in flight
        committed = sorted(
            int(m.group(1))
            for m in (_MANIFEST_RE.search(n_) for n_ in os.listdir(ckpt_dir))
            if m
        )
        kept = set(committed[-keep_last:]) | {snap.epoch}
        # one sweep removes old manifests (uncommit first: the sort below
        # puts each epoch's manifest before its shard files), old shards,
        # AND orphaned shards whose epoch was never committed
        names = sorted(
            os.listdir(ckpt_dir),
            key=lambda n_: (0 if n_.endswith(".manifest.json") else 1, n_),
        )
        for n_ in names:
            if n_.endswith(".corrupt") or ".corrupt." in n_:
                continue  # quarantined files are kept for forensics
            m = _NUMERIC_CKPT_FILE_RE.match(n_)
            if m and int(m.group(1)) not in kept:
                try:
                    os.remove(os.path.join(ckpt_dir, n_))
                except OSError:  # tpu-dist: ignore[TD006] — best-effort prune
                    pass
    return mpath


def publish_sharded_snapshot(
    ckpt_dir: str,
    snap: ShardSnapshot,
    keep_last: Optional[int] = None,
    commit_timeout_s: float = 600.0,
) -> Optional[str]:
    """Phase 2 for the BACKGROUND path: publish this process's shard file,
    then (rank 0) wait for the full shard set via the filesystem barrier
    and commit the manifest. Host-side only — this is what
    :class:`AsyncShardedCheckpointer` runs on its worker thread."""
    _write_shard_file(ckpt_dir, snap)
    if snap.pid != 0:
        return None
    if snap.nproc > 1:
        _await_shard_files(ckpt_dir, snap, commit_timeout_s)
    return _commit_manifest(ckpt_dir, snap, keep_last)


def save_sharded(
    ckpt_dir: str,
    state: TrainState,
    epoch: int,
    keep_last: Optional[int] = None,
    extra_meta: Optional[dict] = None,
    stem: Optional[str] = None,
) -> Optional[str]:
    """Every process writes its own shard file; process 0 commits the
    manifest last. Returns the manifest path on process 0, else None.

    ``stem`` overrides the file-name stem (default ``ckpt_{epoch}``; the
    best-model save uses ``ckpt_best``). ``keep_last`` prunes old EPOCH
    checkpoints (manifest removed first — uncommit — then shard files;
    orphaned shard files of uncommitted epochs are swept too).

    This is the SYNCHRONOUS composition of the two-phase protocol —
    uncommit, snapshot, write, device barrier, commit. The async
    composition (:class:`AsyncShardedCheckpointer`) runs everything after
    the snapshot on a worker thread."""
    stem = stem or f"ckpt_{epoch}"
    _sharded_uncommit(ckpt_dir, stem)
    snap = snapshot_sharded(state, epoch, extra_meta=extra_meta, stem=stem)
    _write_shard_file(ckpt_dir, snap)
    # the manifest is the commit marker: all shard files must exist first
    if snap.nproc > 1:
        from jax.experimental import multihost_utils  # noqa: PLC0415

        multihost_utils.sync_global_devices(f"ckpt_commit_{stem}")
    if snap.pid != 0:
        return None
    return _commit_manifest(ckpt_dir, snap, keep_last)


class ShardedCheckpointer:
    """Drop-in for the module-level save/save_best API, writing the sharded
    format (the Trainer's ``--sharded_ckpt`` adapter)."""

    @staticmethod
    def save(ckpt_dir, state, epoch, keep_last=None, extra_meta=None):
        return save_sharded(
            ckpt_dir, state, epoch, keep_last=keep_last, extra_meta=extra_meta
        )

    @staticmethod
    def save_best(ckpt_dir, state, epoch, metric, extra_meta=None):
        em = dict(extra_meta or {})
        em["metric"] = metric
        return save_sharded(ckpt_dir, state, epoch, extra_meta=em, stem="ckpt_best")


class AsyncShardedCheckpointer(_AsyncWriter):
    """Snapshot-then-write sharded checkpointing (``--sharded_ckpt`` +
    ``--async_ckpt``): the step loop blocks only for the uncommit barrier
    and the device→host :func:`snapshot_sharded`; serialize + CRC32 +
    retry + atomic publish + the manifest commit all run on the worker
    thread (:func:`publish_sharded_snapshot`).

    The cross-process commit barrier moves off the critical path by
    changing mechanism, not semantics: the synchronous path holds a
    ``sync_global_devices`` barrier between shard writes and the manifest;
    the background path has rank 0's writer thread poll the filesystem for
    the full shard set (shard files publish atomically, so existence ⇒
    complete) before committing — a jax collective must never run off the
    main thread. The uncommit barrier STAYS synchronous at submit time:
    it is cheap (one unlink + barrier) and guarantees no stale manifest
    can point at a mixed shard set while the background write replaces
    files. Same EVENTUAL-path contract as :class:`AsyncCheckpointer`:
    the returned manifest path is valid only after ``wait``/``close``;
    write errors (including the injected-EIO fault ladder) surface on the
    next save/wait/close."""

    def __init__(self, commit_timeout_s: float = 600.0) -> None:
        super().__init__()
        self._commit_timeout_s = commit_timeout_s

    def _submit(
        self, ckpt_dir, state, epoch, keep_last, extra_meta, stem
    ) -> Optional[str]:
        if any(getattr(f, "_stem", None) == stem for f in self._pending):
            # an in-flight publish of THIS stem (ckpt_best overwrite, a
            # replayed epoch): drain first so the main-thread uncommit
            # cannot race its background manifest commit
            self.wait()
        _sharded_uncommit(ckpt_dir, stem)
        # the ONLY blocking window: the device→host snapshot
        snap = snapshot_sharded(state, epoch, extra_meta=extra_meta, stem=stem)
        self._harvest(block=False)  # surface finished writes' errors only
        fut = self._pool.submit(
            publish_sharded_snapshot, ckpt_dir, snap,
            keep_last, self._commit_timeout_s,
        )
        fut._stem = stem  # for the same-stem drain guard above
        self._pending.append(fut)
        if snap.pid != 0:
            return None
        return os.path.join(ckpt_dir, f"{stem}.manifest.json")

    def save(
        self, ckpt_dir, state, epoch, keep_last=None, extra_meta=None
    ) -> Optional[str]:
        return self._submit(
            ckpt_dir, state, epoch, keep_last, extra_meta, f"ckpt_{epoch}"
        )

    def save_best(
        self, ckpt_dir, state, epoch, metric, extra_meta=None
    ) -> Optional[str]:
        em = dict(extra_meta or {})
        em["metric"] = metric
        return self._submit(ckpt_dir, state, epoch, None, em, "ckpt_best")


def all_sharded_checkpoints(ckpt_dir: str) -> List[Tuple[str, int]]:
    """Every COMMITTED sharded checkpoint, newest first (manifest paths)."""
    if not os.path.isdir(ckpt_dir):
        return []
    found = []
    for nm in os.listdir(ckpt_dir):
        m = _MANIFEST_RE.search(nm)
        if m:
            found.append((os.path.join(ckpt_dir, nm), int(m.group(1))))
    return sorted(found, key=lambda pe: pe[1], reverse=True)


def latest_sharded_checkpoint(ckpt_dir: str) -> Optional[Tuple[str, int]]:
    """Newest COMMITTED sharded checkpoint: ``(manifest_path, epoch)``."""
    ladder = all_sharded_checkpoints(ckpt_dir)
    return ladder[0] if ladder else None


def verify_sharded(manifest_path: str, deep: bool = True) -> dict:
    """Integrity-check a committed sharded checkpoint: readable manifest,
    the full expected shard-file set, every shard archive readable, every
    stamped entry present, and (``deep=True``) every entry matching its
    shard's ``__crc__`` stamp (pre-stamp shards get the structural checks
    only). ``deep=False`` stops at the archive directories — the
    O(1/n)-per-process choice for multi-process restores, where each
    process would otherwise decompress the WHOLE checkpoint n times
    (restore itself still surfaces piece-level corruption to the ladder).
    Returns the manifest meta; raises :class:`CheckpointCorruptError`."""
    try:
        with open(manifest_path) as f:
            manifest = json.load(f)
        n = manifest["n_shards"]
        ckpt_dir = os.path.dirname(manifest_path)
        stem = os.path.basename(manifest_path)[: -len(".manifest.json")]
        shard_names = sorted(
            nm
            for nm in os.listdir(ckpt_dir)
            if nm.startswith(f"{stem}.shard") and nm.endswith(f"of{n}.npz")
        )
        if len(shard_names) != n:
            raise CheckpointCorruptError(
                f"{manifest_path}: expects {n} shard files, found "
                f"{len(shard_names)} — torn or partially-pruned checkpoint"
            )
        for nm in shard_names:
            spath = os.path.join(ckpt_dir, nm)
            with np.load(spath) as z:
                crcs = None
                if "__crc__" in z.files:
                    crcs = json.loads(bytes(z["__crc__"].tobytes()).decode())
                if crcs is not None:
                    missing = set(crcs) - set(z.files) - {"__crc__"}
                    if missing:
                        raise CheckpointCorruptError(
                            f"{spath}: stamped entries missing from "
                            f"archive: {sorted(missing)[:4]}"
                        )
                if not deep:
                    continue  # zip directory read above is the cheap check
                for k in z.files:
                    if k == "__crc__":
                        continue
                    arr = z[k]
                    if crcs is not None:
                        want = crcs.get(k)
                        if want is None or _entry_crc(arr) != int(want) & 0xFFFFFFFF:
                            raise CheckpointCorruptError(
                                f"{spath}: CRC32 mismatch on {k!r}"
                            )
    except CheckpointCorruptError:
        raise
    except Exception as e:
        raise CheckpointCorruptError(
            f"unreadable sharded checkpoint {manifest_path}: "
            f"{type(e).__name__}: {e}"
        ) from e
    return manifest["meta"]


def read_sharded_meta(manifest_path: str) -> dict:
    with open(manifest_path) as f:
        return json.load(f)["meta"]


def restore_sharded(
    manifest_path: str, template: TrainState, remap=None
) -> TrainState:
    """Rebuild a TrainState shaped (and PLACED) like ``template``.

    Overlap-only reads: each process decompresses just the pieces that
    intersect its own target shards, so restore memory scales with the
    local partition, not the global model (see the section header).

    The manifest's per-entry global shapes + each shard key's slice
    origin/extent make the format mesh-shape-portable: a checkpoint
    written by ``n`` processes restores onto any other process count or
    device sharding by overlap reslice alone whenever the leaf's GLOBAL
    shape is world-size-independent (params, BN, per-leaf momentum).
    Leaves whose global shape bakes in the dp extent (ZeRO-1 flat
    optimizer vectors, error-feedback residuals) go through ``remap``
    (``tpu_dist/elastic/remap.py``): the full checkpoint-global value is
    assembled from its pieces — the allgather-then-reslice fallback —
    remapped to this run's extent, then sliced onto the template's
    shards. Without a hook such leaves raise the typed
    :class:`ElasticShapeMismatch`."""
    # (span: the trainer's restore ladder wraps this whole call — a local
    # span here would cover only the manifest read)
    with open(manifest_path) as f:
        manifest = json.load(f)
    ckpt_dir = os.path.dirname(manifest_path)
    stem = os.path.basename(manifest_path)[: -len(".manifest.json")]
    n = manifest["n_shards"]
    shapes = manifest["shapes"]

    # piece directory from the zip indices only — nothing decompressed yet
    zips = []
    for nm in sorted(os.listdir(ckpt_dir)):
        if nm.startswith(f"{stem}.shard") and nm.endswith(f"of{n}.npz"):
            zips.append(np.load(os.path.join(ckpt_dir, nm)))
    if len(zips) != n:
        for z in zips:
            z.close()
        raise FileNotFoundError(
            f"sharded checkpoint {stem} expects {n} shard files, found "
            f"{len(zips)} — incomplete or mixed ckpt_dir"
        )
    pieces: dict = {}
    for z in zips:
        for skey in z.files:
            if skey == "__crc__":  # per-shard integrity stamp, not a piece
                continue
            key, origin, extent = _parse_shard_key(skey)
            if key not in shapes:
                # a shard/manifest mismatch is corruption, not a template
                # mismatch — typed so the restore ladder can quarantine it
                raise CheckpointCorruptError(
                    f"shard key {key} not in manifest {manifest_path}"
                )
            pieces.setdefault(key, []).append((origin, extent, z, skey))

    def assemble(key, origin, extent, dtype):
        """Host buffer for the [origin, origin+extent) window of ``key``."""
        buf = None
        covered = 0
        for p_org, p_ext, z, skey in pieces[key]:
            lo = tuple(max(a, b) for a, b in zip(origin, p_org))
            hi = tuple(
                min(a + da, b + db)
                for a, da, b, db in zip(origin, extent, p_org, p_ext)
            )
            if any(l >= h for l, h in zip(lo, hi)):
                continue
            if buf is None:
                buf = np.zeros(extent, dtype)
            data = z[skey]  # decompress only overlapping pieces
            src = tuple(
                slice(l - b, h - b) for l, h, b in zip(lo, hi, p_org)
            )
            dst = tuple(
                slice(l - o, h - o) for l, h, o in zip(lo, hi, origin)
            )
            buf[dst] = data[src]
            covered += int(np.prod([h - l for l, h in zip(lo, hi)]))
        if buf is None or covered < int(np.prod(extent)):
            # the manifest committed this leaf but the shard set cannot
            # rebuild it: lost/partial shard data — ladder-quarantinable
            raise CheckpointCorruptError(
                f"sharded checkpoint does not cover {key}"
                f"[{origin}:+{extent}] (covered {covered} elements)"
            )
        return buf

    tdict = template._asdict()
    paths_leaves, treedef = jax.tree_util.tree_flatten_with_path(tdict)
    out = []
    try:
        for path, leaf in paths_leaves:
            key = jax.tree_util.keystr(path)
            if key not in pieces:
                zero = _missing_ok(key, leaf)
                if zero is None:
                    raise KeyError(f"checkpoint missing array for {key}")
                if not isinstance(leaf, jax.Array):
                    out.append(zero if zero.shape else zero[()])
                    continue
                parts = [
                    jax.device_put(np.zeros(np.shape(sh.data), zero.dtype), sh.device)
                    for sh in leaf.addressable_shards
                ]
                out.append(
                    jax.make_array_from_single_device_arrays(
                        zero.shape, leaf.sharding, parts
                    )
                )
                continue
            gshape = tuple(shapes[key])
            dtype = np.dtype(
                leaf.dtype if hasattr(leaf, "dtype") else np.asarray(leaf).dtype
            )
            if tuple(np.shape(leaf)) != gshape:
                # dp-extent-dependent leaf saved at another world size:
                # assemble the FULL checkpoint-global value from its
                # pieces (the allgather-then-reslice fallback — these are
                # flat vectors, not the bulk params) and run the elastic
                # hook; _resolve raises the typed error without one
                full = assemble(key, (0,) * len(gshape), gshape, dtype)
                remapped = np.asarray(
                    _resolve_shape_mismatch(remap, key, full, leaf, tdict)
                ).astype(dtype)
                if not isinstance(leaf, jax.Array):
                    out.append(
                        remapped if np.shape(remapped) else remapped[()]
                    )
                    continue
                parts = [
                    jax.device_put(
                        np.ascontiguousarray(remapped[sh.index]), sh.device
                    )
                    for sh in leaf.addressable_shards
                ]
                out.append(
                    jax.make_array_from_single_device_arrays(
                        tuple(np.shape(leaf)), leaf.sharding, parts
                    )
                )
                continue
            if not isinstance(leaf, jax.Array):
                full = assemble(key, (0,) * len(gshape), gshape, dtype)
                out.append(full if gshape else full[()])
                continue
            cache: dict = {}
            parts = []
            for sh in leaf.addressable_shards:
                origin = tuple(sl.start or 0 for sl in sh.index)
                extent = tuple(np.shape(sh.data))
                buf = cache.get(origin)
                if buf is None:
                    buf = assemble(key, origin, extent, dtype)
                    cache[origin] = buf
                parts.append(jax.device_put(buf, sh.device))
            out.append(
                jax.make_array_from_single_device_arrays(
                    gshape, leaf.sharding, parts
                )
            )
    finally:
        for z in zips:
            z.close()
    d: Any = jax.tree_util.tree_unflatten(treedef, out)
    return TrainState(**d)
