"""Process-0 checkpoint / resume.

Makes real what the reference only documents: the rank-0-guarded model save
(``tutorials/2:§7``), the dead ``save_epoch`` knob (``utils/config.py:7``)
and the reserved ``/ckpts`` directory (``.gitignore:4``). Saves the whole
:class:`TrainState` (params, BN stats, momentum buffers, step) plus the
epoch — enough for exact resume.

Format: one ``.npz`` of flattened arrays keyed by pytree path + a JSON
sidecar with the epoch and keys. Atomic via write-to-temp + rename. Only
process 0 writes (single-writer discipline); every process can read.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Optional, Tuple

import jax
import numpy as np

from tpu_dist.train.state import TrainState

_CKPT_RE = re.compile(r"ckpt_(\d+)\.npz$")


def _leaf_to_host(leaf) -> np.ndarray:
    """Bring one leaf fully to host. Leaves sharded across processes (ZeRO-1
    opt state under P('data'), TP-sharded params on a multi-host mesh) are
    not addressable from process 0 alone — gather them collectively first.
    NOTE: collective ⇒ every process must reach this call (see save())."""
    if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
        from jax.experimental import multihost_utils  # noqa: PLC0415

        return np.asarray(multihost_utils.process_allgather(leaf, tiled=True))
    return np.asarray(jax.device_get(leaf))


def _flatten(tree) -> dict:
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = jax.tree_util.keystr(path)
        flat[key] = _leaf_to_host(leaf)
    return flat


def _unflatten(template, flat: dict):
    paths_leaves, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves = []
    for path, leaf in paths_leaves:
        key = jax.tree_util.keystr(path)
        if key not in flat:
            raise KeyError(f"checkpoint missing array for {key}")
        arr = flat[key]
        if tuple(arr.shape) != tuple(leaf.shape):
            raise ValueError(f"shape mismatch for {key}: ckpt {arr.shape} vs state {leaf.shape}")
        leaves.append(arr.astype(leaf.dtype))
    return jax.tree_util.tree_unflatten(treedef, [l for l in leaves])


def _write_npz(
    ckpt_dir: str, name: str, flat: dict, meta: dict,
    keep_last: Optional[int] = None,
) -> str:
    """Serialize + atomically publish one checkpoint file (host-side only —
    safe to run on a worker thread; ``flat`` holds host numpy copies)."""
    os.makedirs(ckpt_dir, exist_ok=True)
    flat = dict(flat)
    flat["__meta__"] = np.frombuffer(json.dumps(meta).encode(), dtype=np.uint8)
    path = os.path.join(ckpt_dir, name)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **flat)
    os.replace(tmp, path)  # atomic: a ckpt file is either absent or complete
    if keep_last is not None and keep_last > 0:
        epochs = sorted(
            int(m.group(1))
            for m in (_CKPT_RE.search(n) for n in os.listdir(ckpt_dir))
            if m
        )
        for e in epochs[:-keep_last]:
            try:
                os.remove(os.path.join(ckpt_dir, f"ckpt_{e}.npz"))
            except OSError:
                pass
    return path


def save(
    ckpt_dir: str,
    state: TrainState,
    epoch: int,
    keep_last: Optional[int] = None,
    extra_meta: Optional[dict] = None,
) -> Optional[str]:
    """Write ``ckpt_{epoch}.npz``; no-op off process 0 (rank-0 guard).

    ``keep_last``: prune to the N newest checkpoints after writing.
    ``extra_meta``: extra JSON-serializable keys for the sidecar (e.g. the
    pipeline layout tag — interleaved storage permutes block order, so a
    resume under a different ``pp_interleave`` must be refused, not run
    silently wrong)."""
    # flatten BEFORE the rank-0 guard: gathering cross-process-sharded
    # leaves is collective, so every process must participate
    flat = _flatten(state._asdict())
    if jax.process_index() != 0:
        return None
    meta = {"epoch": epoch, "step": int(jax.device_get(state.step))}
    if extra_meta:
        meta.update(extra_meta)
    return _write_npz(ckpt_dir, f"ckpt_{epoch}.npz", flat, meta, keep_last)


def save_best(
    ckpt_dir: str,
    state: TrainState,
    epoch: int,
    metric: float,
    extra_meta: Optional[dict] = None,
) -> Optional[str]:
    """Write/overwrite ``ckpt_best.npz`` (rank-0, atomic) tagging the metric."""
    flat = _flatten(state._asdict())  # collective: before the rank-0 guard
    if jax.process_index() != 0:
        return None
    meta = {"epoch": epoch, "metric": metric}
    if extra_meta:
        meta.update(extra_meta)
    return _write_npz(ckpt_dir, "ckpt_best.npz", flat, meta)


class AsyncCheckpointer:
    """Overlap checkpoint WRITES with training (the orbax-style async-save
    pattern, self-contained).

    The device→host snapshot (``_flatten``) stays synchronous — it is the
    data dependency on the live ``TrainState`` and, multi-host, a
    collective every process must join. The expensive part (npz
    serialization + atomic rename + pruning) runs on a single worker
    thread over the host copies, so the train loop resumes immediately.

    Publish order is the submission order (one worker thread). A save
    never blocks on an earlier write still in flight — it only harvests
    ALREADY-finished writes to surface their errors; ``wait()`` blocks on
    everything outstanding and re-raises the first writer error. Call
    ``wait()`` (or ``close()``, which also releases the worker thread)
    before process exit — the Trainer does, at the end of ``fit()`` and in
    the interrupt path.
    """

    def __init__(self) -> None:
        from concurrent.futures import ThreadPoolExecutor  # noqa: PLC0415

        self._pool = ThreadPoolExecutor(max_workers=1, thread_name_prefix="ckpt")
        self._pending: list = []

    def _harvest(self, block: bool) -> None:
        first_err = None
        while self._pending and (block or self._pending[0].done()):
            fut = self._pending.pop(0)
            try:
                fut.result()
            except Exception as e:  # keep draining; re-raise the first
                if first_err is None:
                    first_err = e
        if first_err is not None:
            raise first_err

    def wait(self) -> None:
        """Block until every outstanding write is published; re-raises the
        first writer-thread exception here."""
        self._harvest(block=True)

    def close(self) -> None:
        """``wait()`` then release the worker thread. The instance is dead
        afterwards (a new save would raise from the shut-down pool)."""
        try:
            self.wait()
        finally:
            self._pool.shutdown(wait=True)

    def save(
        self,
        ckpt_dir: str,
        state: TrainState,
        epoch: int,
        keep_last: Optional[int] = None,
        extra_meta: Optional[dict] = None,
    ) -> Optional[str]:
        """Snapshot synchronously, write in the background; returns the
        EVENTUAL path. The file exists only after the background write
        publishes — call :meth:`wait` (or :meth:`close`) before reading
        the path or relying on it surviving a crash; write errors surface
        on the next save/wait/close, not here. The Trainer drains via
        ``wait()`` at epoch boundaries and ``close()`` on exit."""
        flat = _flatten(state._asdict())  # sync: collective + host snapshot
        if jax.process_index() != 0:
            return None
        self._harvest(block=False)  # surface finished writes' errors only
        meta = {"epoch": epoch, "step": int(jax.device_get(state.step))}
        if extra_meta:
            meta.update(extra_meta)
        self._pending.append(self._pool.submit(
            _write_npz, ckpt_dir, f"ckpt_{epoch}.npz", flat, meta, keep_last
        ))
        return os.path.join(ckpt_dir, f"ckpt_{epoch}.npz")

    def save_best(
        self,
        ckpt_dir: str,
        state: TrainState,
        epoch: int,
        metric: float,
        extra_meta: Optional[dict] = None,
    ) -> Optional[str]:
        """Best-model twin of :meth:`save` — same EVENTUAL-path contract:
        the returned path is valid only after :meth:`wait`/:meth:`close`."""
        flat = _flatten(state._asdict())
        if jax.process_index() != 0:
            return None
        self._harvest(block=False)
        meta = {"epoch": epoch, "metric": metric}
        if extra_meta:
            meta.update(extra_meta)
        self._pending.append(self._pool.submit(
            _write_npz, ckpt_dir, "ckpt_best.npz", flat, meta
        ))
        return os.path.join(ckpt_dir, "ckpt_best.npz")


def latest_checkpoint(ckpt_dir: str) -> Optional[Tuple[str, int]]:
    """Returns ``(path, epoch)`` of the newest complete checkpoint."""
    if not os.path.isdir(ckpt_dir):
        return None
    best = None
    for name in os.listdir(ckpt_dir):
        m = _CKPT_RE.search(name)
        if m:
            e = int(m.group(1))
            if best is None or e > best[1]:
                best = (os.path.join(ckpt_dir, name), e)
    return best


def read_meta(path: str) -> dict:
    """The JSON sidecar of a checkpoint (epoch, step, any extra_meta)."""
    with np.load(path) as z:
        if "__meta__" not in z.files:
            return {}
        return json.loads(bytes(z["__meta__"].tobytes()).decode())


def restore(path: str, template: TrainState) -> TrainState:
    """Rebuild a TrainState shaped like ``template`` from ``path``.

    Arrays come back as host numpy; the caller re-places them on the mesh
    (the trainer does this when resuming).
    """
    with np.load(path) as z:
        flat = {k: z[k] for k in z.files if k != "__meta__"}
    d: Any = _unflatten(template._asdict(), flat)
    return TrainState(**d)
