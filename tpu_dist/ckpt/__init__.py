from tpu_dist.ckpt.checkpoint import latest_checkpoint, restore, save  # noqa: F401
