from tpu_dist.ckpt.checkpoint import (  # noqa: F401
    AsyncCheckpointer,
    ShardedCheckpointer,
    latest_checkpoint,
    latest_sharded_checkpoint,
    read_meta,
    read_sharded_meta,
    restore,
    restore_sharded,
    save,
    save_best,
    save_sharded,
)
