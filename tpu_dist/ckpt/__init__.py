from tpu_dist.ckpt.checkpoint import (  # noqa: F401
    AsyncCheckpointer,
    latest_checkpoint,
    read_meta,
    restore,
    save,
    save_best,
)
