from tpu_dist.ckpt.checkpoint import latest_checkpoint, restore, save, save_best  # noqa: F401
