"""Tracing / profiling hooks (SURVEY §5).

The reference's observability is wall-clock ``time.time()`` pairs printed on
rank 0 (``distributed.py:78,113-115``) — kept, in the Trainer's epoch
timing. This module adds what the reference lacks:

* :func:`trace` — capture an XLA/TPU profile (TensorBoard-compatible, holds
  HLO timelines, memory, and ICI collectives) around any code region via
  ``jax.profiler``.
* :class:`StepTimer` — cheap steady-state step timing with warmup skip;
  feeds the seconds/epoch and images/sec/chip numbers BASELINE.json asks
  for without device-sync overhead in the hot loop.
* :func:`annotate_step` — names the current step in captured traces.
"""

from __future__ import annotations

import contextlib
import time
from typing import Iterator, Optional

import jax


@contextlib.contextmanager
def trace(logdir: str, *, primary_only: bool = True) -> Iterator[None]:
    """Profile a region to ``logdir`` (view with TensorBoard's profile tab).

    ``primary_only`` keeps the rank-0 discipline: other processes run the
    region untraced.
    """
    if primary_only and jax.process_index() != 0:
        yield
        return
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate_step(step: int):
    """Mark a training step in profiles (shows as a named range)."""
    return jax.profiler.StepTraceAnnotation("train_step", step_num=step)


class StepTimer:
    """Steady-state throughput: skips warmup/compile steps, no per-step
    device sync (the device queue keeps the TPU busy; only ``finish`` blocks).

    Beyond the mean, each post-warmup ``tick`` records a per-step lap on
    the monotonic clock, so the trainer's epoch summary can report tail
    latency (:meth:`percentiles`) — the p99 is where input stalls and
    stragglers live; a mean hides them completely."""

    def __init__(self, warmup_steps: int = 3):
        self.warmup_steps = warmup_steps
        self._seen = 0
        self._t0: Optional[float] = None
        self._last: Optional[float] = None
        self.steps = 0
        self.laps: list = []  # post-warmup per-step seconds, tick-to-tick

    def tick(self) -> None:
        now = time.perf_counter()
        self._seen += 1
        if self._seen == self.warmup_steps:
            self._t0 = now
            self._last = now
        elif self._seen > self.warmup_steps:
            self.steps += 1
            if self._last is not None:
                self.laps.append(now - self._last)
            self._last = now

    def finish(self, blocker=None) -> Optional[float]:
        """Seconds per steady-state step (None if too few steps).
        ``blocker``: array to ``block_until_ready`` before reading the clock."""
        if blocker is not None:
            jax.block_until_ready(blocker)
        if self._t0 is None or self.steps == 0:
            return None
        return (time.perf_counter() - self._t0) / self.steps

    def percentiles(self, qs=(50, 95, 99)) -> Optional[dict]:
        """``{"p50": s, "p95": s, "p99": s}`` over the recorded laps
        (nearest-rank; None with no laps — e.g. a 1-step epoch where every
        step was warmup)."""
        if not self.laps:
            return None
        laps = sorted(self.laps)
        n = len(laps)
        return {
            f"p{q}": laps[min(n - 1, max(0, int(round(q / 100.0 * n)) - 1))]
            for q in qs
        }
