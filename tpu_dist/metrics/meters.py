"""Running-average meters and progress display.

Fills the role of the reference's metrics kit (``utils/util.py:11-48``) and
keeps its *display contract* — ``loss 1.23 (1.50)`` per meter and
``[ 12/196]`` step counters — but is this repo's own implementation: a
running-sum core behind read-only properties, rendering via :func:`format`
with a plain format-spec, and a progress line built from string padding
rather than assembled format templates.

The cross-replica part of the reference kit (``reduce_mean``,
``utils/util.py:5-9``) lives in ``tpu_dist.comm.collectives`` and — in the
hot path — inside the compiled step, so meters here only ever see
already-reduced host scalars.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax


@dataclass
class AverageMeter:
    """Tracks the latest value and the n-weighted running mean of a scalar.

    ``fmt`` is a format spec (with or without the leading ``:``) applied to
    both the latest and the mean value in ``str(meter)``.
    """

    name: str
    fmt: str = ":f"
    _total: float = field(default=0.0, repr=False)
    _weight: int = field(default=0, repr=False)
    _latest: float = field(default=0.0, repr=False)

    @property
    def val(self) -> float:
        return self._latest

    @property
    def sum(self) -> float:
        return self._total

    @property
    def count(self) -> int:
        return self._weight

    @property
    def avg(self) -> float:
        return self._total / self._weight if self._weight else 0.0

    def reset(self) -> None:
        self._total, self._weight, self._latest = 0.0, 0, 0.0

    def update(self, val: float, n: int = 1) -> None:
        self._latest = float(val)
        self._total += self._latest * n
        self._weight += n

    def __str__(self) -> str:
        spec = self.fmt.lstrip(":")
        return f"{self.name} {format(self.val, spec)} ({format(self.avg, spec)})"


class ProgressMeter:
    """Prints a tab-joined progress line: a ``[ cur/total]`` step counter
    (current padded to total's width) followed by each meter's ``str``."""

    def __init__(self, num_batches: int, *meters: AverageMeter, prefix: str = ""):
        self.num_batches = num_batches
        self.meters = list(meters)
        self.prefix = prefix

    def _counter(self, batch: int) -> str:
        total = str(self.num_batches)
        return f"[{str(batch).rjust(len(total))}/{total}]"

    def display(self, batch: int) -> str:
        line = "\t".join(
            [self.prefix + self._counter(batch), *map(str, self.meters)]
        )
        # rank-0 discipline lives HERE, not at call sites: the reference
        # guards every progress.display() behind `if rank == 0` and our
        # evaluation loop did not — printing from each host duplicates the
        # line world_size times (analysis rule TD002 caught it).
        if jax.process_index() == 0:
            print(line, flush=True)
        return line
