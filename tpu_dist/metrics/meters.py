"""Running-average meters and progress display.

API-parity with the reference's metrics kit (``utils/util.py:11-48``):
``AverageMeter(name, fmt)`` keeps val/avg/sum/count with the same ``__str__``
format; ``ProgressMeter(num_batches, meters, prefix)`` prints the same
``[ 12/196] loss 1.23 (1.50)`` lines. The cross-replica part of the
reference kit (``reduce_mean``, ``utils/util.py:5-9``) lives in
``tpu_dist.comm.collectives`` and — in the hot path — inside the compiled
step, so meters here only ever see already-reduced host scalars.
"""

from __future__ import annotations


class AverageMeter:
    """Computes and stores the average and current value."""

    def __init__(self, name: str, fmt: str = ":f"):
        self.name = name
        self.fmt = fmt
        self.reset()

    def reset(self) -> None:
        self.val = 0.0
        self.avg = 0.0
        self.sum = 0.0
        self.count = 0

    def update(self, val: float, n: int = 1) -> None:
        val = float(val)
        self.val = val
        self.sum += val * n
        self.count += n
        self.avg = self.sum / max(self.count, 1)

    def __str__(self) -> str:
        fmtstr = "{name} {val" + self.fmt + "} ({avg" + self.fmt + "})"
        return fmtstr.format(**self.__dict__)


class ProgressMeter:
    def __init__(self, num_batches: int, *meters: AverageMeter, prefix: str = ""):
        self.batch_fmtstr = self._get_batch_fmtstr(num_batches)
        self.meters = meters
        self.prefix = prefix

    def display(self, batch: int) -> str:
        entries = [self.prefix + self.batch_fmtstr.format(batch)]
        entries += [str(m) for m in self.meters]
        line = "\t".join(entries)
        print(line, flush=True)
        return line

    @staticmethod
    def _get_batch_fmtstr(num_batches: int) -> str:
        num_digits = len(str(num_batches))
        fmt = "{:" + str(num_digits) + "d}"
        return "[" + fmt + "/" + fmt.format(num_batches) + "]"
