"""Minimal TensorBoard event-file writer — no TensorFlow dependency.

The reference RESERVES TensorBoard support but never builds it: the dead
config knob ``tensorboard_dir='runs'`` (``utils/config.py:8``) and the
``.gitignore`` slot for ``/runs`` (``.gitignore:5``) are the whole
feature. This module makes it real, self-contained: it hand-encodes the
two protobuf messages TensorBoard's scalar dashboard needs (``Event`` and
``Summary.Value.simple_value``) and frames them in the TFRecord format
(length + masked-CRC32C), producing ``events.out.tfevents.*`` files any
stock TensorBoard install reads. Verified against TensorBoard's own
``event_accumulator`` reader in ``tests/test_tensorboard.py``.

Host-side, rank-0-only (single-writer discipline like the checkpoint
layer); pure stdlib so the TPU image needs no extra packages.
"""

from __future__ import annotations

import os
import socket
import struct
import time
from typing import Optional

# -- CRC32C (Castagnoli, reflected poly 0x82F63B78) — software table ---------

_CRC_TABLE = []
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (_c >> 1) ^ 0x82F63B78 if _c & 1 else _c >> 1
    _CRC_TABLE.append(_c)


def _crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# -- protobuf wire encoding (just the fields the scalar dashboard reads) -----


def _varint(n: int) -> bytes:
    n &= (1 << 64) - 1  # two's-complement int64, protobuf-style
    out = bytearray()
    while True:
        bits = n & 0x7F
        n >>= 7
        if n:
            out.append(bits | 0x80)
        else:
            out.append(bits)
            return bytes(out)


def _field_double(num: int, v: float) -> bytes:
    return bytes([(num << 3) | 1]) + struct.pack("<d", v)


def _field_float(num: int, v: float) -> bytes:
    return bytes([(num << 3) | 5]) + struct.pack("<f", v)


def _field_varint(num: int, v: int) -> bytes:
    return bytes([(num << 3) | 0]) + _varint(v)


def _field_bytes(num: int, payload: bytes) -> bytes:
    return bytes([(num << 3) | 2]) + _varint(len(payload)) + payload


def _scalar_event(tag: str, value: float, step: int, wall_time: float) -> bytes:
    value_msg = _field_bytes(1, tag.encode()) + _field_float(2, float(value))
    summary = _field_bytes(1, value_msg)          # Summary.value (repeated)
    return (
        _field_double(1, wall_time)               # Event.wall_time
        + _field_varint(2, int(step))             # Event.step
        + _field_bytes(5, summary)                # Event.summary
    )


def _version_event(wall_time: float) -> bytes:
    return _field_double(1, wall_time) + _field_bytes(3, b"brain.Event:2")


class SummaryWriter:
    """Append-only scalar event writer for one run directory.

    ``SummaryWriter(logdir).add_scalar("train/loss", 1.23, step)`` — same
    call shape as torch.utils.tensorboard, covering the slice of it the
    reference's (never-built) integration would have used."""

    def __init__(self, logdir: str):
        os.makedirs(logdir, exist_ok=True)
        name = (
            f"events.out.tfevents.{int(time.time())}."
            f"{socket.gethostname()}.{os.getpid()}"
        )
        self.path = os.path.join(logdir, name)
        # tpu-dist: ignore[TD002] — torch convention: the writer is only
        # constructed on the primary process (trainer guards is_primary())
        self._f = open(self.path, "ab")
        self._record(_version_event(time.time()))

    def _record(self, payload: bytes) -> None:
        header = struct.pack("<Q", len(payload))
        self._f.write(
            header
            + struct.pack("<I", _masked_crc(header))
            + payload
            + struct.pack("<I", _masked_crc(payload))
        )

    def add_scalar(self, tag: str, value: float, step: int,
                   wall_time: Optional[float] = None) -> None:
        self._record(
            _scalar_event(
                tag, value, step,
                time.time() if wall_time is None else wall_time,
            )
        )
        # flush per scalar: records are ~50 bytes and writes are per-epoch,
        # so buffering buys nothing — a LIVE TensorBoard must see the run
        self._f.flush()

    def flush(self) -> None:
        self._f.flush()

    def close(self) -> None:
        if not self._f.closed:
            self._f.flush()
            self._f.close()

    def __enter__(self) -> "SummaryWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
