"""Rank-0 logging discipline (reference ``tutorials/2:§3``; guard pattern at
``distributed.py:103,114``): only the primary process prints/logs."""

from __future__ import annotations

import logging
import sys

import jax


def rank0_print(*args, **kwargs) -> None:
    if jax.process_index() == 0:
        print(*args, **kwargs, flush=True)


def get_logger(name: str = "tpu_dist") -> logging.Logger:
    """Logger that is a no-op on non-primary processes."""
    logger = logging.getLogger(name)
    if not logger.handlers:
        if jax.process_index() == 0:
            h = logging.StreamHandler(sys.stdout)
            h.setFormatter(logging.Formatter("%(asctime)s %(levelname)s %(message)s"))
            logger.addHandler(h)
            logger.setLevel(logging.INFO)
        else:
            logger.addHandler(logging.NullHandler())
            logger.setLevel(logging.CRITICAL)
    return logger
