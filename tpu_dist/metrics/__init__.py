from tpu_dist.metrics.meters import AverageMeter, ProgressMeter  # noqa: F401
from tpu_dist.metrics.logging import get_logger, rank0_print  # noqa: F401
