"""JSONL metrics history — file-based observability the reference reserves
but never builds (``.gitignore:3`` ignores ``/log``; tensorboard knob dead
in ``utils/config.py:8``). One JSON object per line, append-only, rank-0
only; consumable by pandas/jq/tensorboard-importers and by
``python -m tpu_dist.obs summarize`` (docs/observability.md).

Schema (version 6): every record carries

* ``ts`` — wall clock (epoch seconds; for humans and cross-run joins),
* ``rel_s`` — monotonic seconds since this history opened (immune to NTP
  steps; what offline latency math should use),
* ``schema_version`` and, when the owner passed one, ``run_id`` (config
  hash + start time stamped ONCE at construction — not re-derived per
  record, so every line of a run agrees),
* ``kind`` plus the caller's fields,
* ``counters`` — a snapshot of the process-global telemetry registry
  (``tpu_dist.obs.counters``), when non-empty; the summarize CLI turns
  successive snapshots into per-epoch deltas.

Version history: v2 added ``rel_s``/``run_id``/``counters``; v3 added the
device-health layer — ``device_stats`` and ``anomaly`` record kinds and
the ``mfu`` field on ``train_epoch``; v4 added the fleet layer —
``goodput`` (per-window wall-clock buckets + a run-end ``final`` totals
record) and ``profile`` (triggered device-capture events) kinds; v5
added the live layer — the ``alert`` kind (a declarative threshold rule
fired: rule/metric/value/threshold/sustained, ``obs/alerts.py``); v6
added the analytics layer — the ``profile_analysis`` kind (per-capture
device-time attribution read back from the trace by ``obs/xprof.py``:
category seconds, collectives by kind, comm/compute overlap fraction,
infeed stall, top ops, cost-model ``calibration`` gauges); v7 added the
elastic layer — the ``resume`` segment-boundary kind; v8 added the fleet
layer — the ``fleet`` kind (a scheduler chip-move decision with the
allocations before/after and the scraped signals that justified it); v9
added the forensics layer — the ``postmortem`` kind (a crash bundle
assembled from a dead run's leftover files: per-rank verdicts, stuck
frames, last flight-ring steps — ``obs/postmortem.py``, appended by the
watchdog's auto-invoke rather than by the dying run itself); v10 added
the serving layer — the ``serve`` kind (one SLO observation window per
record: latency percentile bounds, requests/s, availability, batch
occupancy, per-phase latency sums, a compact latency histogram —
``tpu_dist/serve``, docs/serving.md); v11 added the memory layer — the
``memory`` kind (the HBM ledger captured at first dispatch: static
per-leaf accounting from avals+shardings, the ``memory_analysis()``
waterfall, a live-buffer census reconciled against the allocator so
attributed + unattributed == bytes_in_use exactly; ``event: "oom"``
records carry a parsed RESOURCE_EXHAUSTED report plus the ledger
snapshot live at the crash — ``obs/memory.py``, docs/observability.md
"HBM ledger & OOM forensics"); v12 added the planner layer — the
``plan`` kind (the ``--auto_shard`` plan chosen at fit() start: family,
mode, predicted step time, gauge source; after a profiled run a second
``plan`` record lands with the achieved step time and the TD119
``planner_error_frac`` drift scalar — ``tpu_dist/analysis/planner.py``,
docs/planner.md); v13 added the tuner layer — the ``tune`` kind (the
``--tune_report`` knob application at fit() start: the config's planner
family, the schedule knobs actually applied, explicit user overrides
kept, and the tuner objective; the same knobs ride the counter snapshot
as ``tune.*`` gauges — ``tpu_dist/analysis/overlap.py``,
docs/analysis.md)
(docs/observability.md). Consumers (``obs summarize``/``compare``) read
all versions: every addition is a new kind or optional field, never a
changed one, and readers skip-with-count kinds they don't know — so a
v4 reader tolerates a v5 log the same way a v5 reader tolerates a v6
one.

The file handle is opened once, line-buffered, and reused — the previous
open-per-``log()`` implementation paid a file open/close every record and
could interleave badly with slow filesystems.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

import jax

from tpu_dist.obs import counters as counters_lib

SCHEMA_VERSION = 15  # v15 (additive): causal arbitration tracing —
#                      'resume'/'fleet'/'tenancy' records carry the
#                      scheduler's monotonic decision_id (+
#                      decision_cause on resumes), and the goodput
#                      ledger splits preempt_for_serve_s out of
#                      recovery_s off that cause
#                      (tpu_dist/fleet/scheduler.py, obs/goodput.py);
#                      v14 added 'tenancy' records — the fleet
#                      scheduler's per-tick chip-accounting snapshots
#                      (alloc/free/pending; tpu_dist/fleet/scheduler.py)
#                      whose sums make chip-second conservation exact;
#                      v13 added 'tune' records — the --tune_report
#                      overlap-autotuner knob application + tune.* gauges
#                      (tpu_dist/analysis/overlap.py); v12 added 'plan'
#                      records — the --auto_shard chosen plan + TD119
#                      predicted-vs-achieved planner_error_frac
#                      (tpu_dist/analysis/planner.py); v11 'memory'
#                      HBM-ledger records (tpu_dist/obs/memory.py);
#                      v10 'serve' serving-SLO windows; v9 'postmortem'
#                      crash bundles; v8 'fleet' scheduler decisions;
#                      v7 'resume' segment boundaries


class MetricsHistory:
    def __init__(
        self,
        path: Optional[str],
        run_id: Optional[str] = None,
        t0: Optional[float] = None,
        all_processes: bool = False,
    ):
        """``path=None`` disables (and any non-primary process is a no-op
        unless ``all_processes`` — the Trainer's ``--per_host_log``, where
        every process writes its own rank-suffixed file for ``obs pod``
        aggregation; the caller owns making the paths distinct).
        ``run_id`` identifies the run in every record; the Trainer passes
        its config-hash + start-time stamp. ``t0`` (a ``time.monotonic()``
        reading) overrides the ``rel_s`` origin — the Trainer passes its
        construction instant, the SAME origin its span recorder zeroes at,
        so exported epoch bars and host spans share one timeline."""
        self.path = path if (
            path and (all_processes or jax.process_index() == 0)
        ) else None
        self.run_id = run_id
        self._f = None
        self._t0 = t0 if t0 is not None else time.monotonic()
        if self.path:
            os.makedirs(os.path.dirname(os.path.abspath(self.path)), exist_ok=True)
            # tpu-dist: ignore[TD002] — self.path is None off rank 0 (guard
            # in __init__) unless the caller opted into per-process files
            # (all_processes, distinct rank-suffixed paths), so this handle
            # never contends cross-process.
            # buffering=1: line-buffered — each record is flushed whole, so
            # tail -f / a concurrent summarize sees complete lines only.
            self._f = open(self.path, "a", buffering=1)

    def log(self, kind: str, **fields) -> None:
        if self._f is None:
            return
        rec = {
            "ts": round(time.time(), 3),
            "rel_s": round(time.monotonic() - self._t0, 3),
            "schema_version": SCHEMA_VERSION,
            "kind": kind,
        }
        if self.run_id:
            rec["run_id"] = self.run_id
        rec.update({k: (float(v) if hasattr(v, "item") else v) for k, v in fields.items()})
        if "counters" not in rec:
            snap = counters_lib.snapshot()
            if snap:
                rec["counters"] = snap
        self._f.write(json.dumps(rec) + "\n")

    def close(self) -> None:
        if self._f is not None:
            f, self._f = self._f, None
            f.close()

    def __enter__(self) -> "MetricsHistory":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):  # belt-and-braces: the Trainer close()s explicitly
        try:
            self.close()
        except Exception:  # tpu-dist: ignore[TD006] — __del__ runs at
            pass  # interpreter teardown where raising is forbidden anyway
