"""JSONL metrics history — file-based observability the reference reserves
but never builds (``.gitignore:3`` ignores ``/log``; tensorboard knob dead
in ``utils/config.py:8``). One JSON object per line, append-only, rank-0
only; consumable by pandas/jq/tensorboard-importers alike.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

import jax


class MetricsHistory:
    def __init__(self, path: Optional[str]):
        """``path=None`` disables (and any non-primary process is a no-op)."""
        self.path = path if (path and jax.process_index() == 0) else None
        if self.path:
            os.makedirs(os.path.dirname(os.path.abspath(self.path)), exist_ok=True)

    def log(self, kind: str, **fields) -> None:
        if not self.path:
            return
        rec = {"ts": round(time.time(), 3), "kind": kind}
        rec.update({k: (float(v) if hasattr(v, "item") else v) for k, v in fields.items()})
        # tpu-dist: ignore[TD002] — self.path is None off rank 0 (guard in
        # __init__), so this append only ever runs on the primary process
        with open(self.path, "a") as f:
            f.write(json.dumps(rec) + "\n")
