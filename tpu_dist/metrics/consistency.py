"""Debug-mode cross-replica consistency checks (SURVEY §5 race-detection).

The reference guards against divergence with ``dist.barrier()`` before every
metric reduction (``distributed.py:95``) — pedagogy, not necessity. Under
XLA, ordering is dataflow; the failure mode that remains is REPLICA STATE
DIVERGENCE (e.g. non-deterministic host input, a collective dropped from a
custom step). This module detects exactly that: assert that nominally
replicated values really are bitwise-equal across every device.
"""

from __future__ import annotations

import jax
import numpy as np


def check_replicated(tree, name: str = "state", atol: float = 0.0) -> None:
    """Assert every leaf is identical on all devices holding it.

    Works on replicated (fully-addressable) arrays — fetches each device's
    shard and compares against device 0's. Raises ``AssertionError`` naming
    the first diverging leaf. Intended for debug runs / tests, not the hot
    loop.
    """
    leaves_with_paths = jax.tree_util.tree_flatten_with_path(tree)[0]
    for path, leaf in leaves_with_paths:
        if not isinstance(leaf, jax.Array) or not leaf.is_fully_addressable:
            continue
        shards = leaf.addressable_shards
        if len(shards) <= 1:
            continue
        ref = np.asarray(shards[0].data)
        for s in shards[1:]:
            got = np.asarray(s.data)
            if ref.shape != got.shape:
                continue  # sharded (not replicated) leaf — not our concern
            if atol == 0.0:
                ok = np.array_equal(ref, got, equal_nan=True)
            else:
                ok = np.allclose(ref, got, atol=atol, equal_nan=True)
            if not ok:
                key = jax.tree_util.keystr(path)
                raise AssertionError(
                    f"replica divergence in {name}{key}: device {shards[0].device} "
                    f"vs {s.device} (max abs diff "
                    f"{np.max(np.abs(ref - got)):.3e})"
                )
