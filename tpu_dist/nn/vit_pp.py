"""Pipeline-parallel ViT: transformer blocks sharded into stages over a
``pipe`` mesh axis, microbatches streamed GPipe-style.

No reference counterpart (SURVEY §2.3: no PP anywhere). Design: the
embed/positional/head layers are small and stay replicated (computed on
every device); only the uniform transformer-block stack is pipelined —
each device owns ``depth / n_stages`` consecutive blocks, held as STACKED
arrays (leading block dim) so one ``P('pipe')`` spec shards them. A stage
runs its blocks with a ``lax.scan``; stage handoff is
``tpu_dist.parallel.pipeline.pipeline_apply``'s ``ppermute`` ring.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from tpu_dist.comm import compat

from tpu_dist.nn.vit import (
    ViTDef,
    _dense,
    _ln_apply,
    block_forward,
    check_pos_capacity,
    patchify,
    tp_block_forward,
)
from tpu_dist.parallel.pipeline import pipeline_apply, pipeline_apply_interleaved


@dataclass(frozen=True)
class ViTPipelineDef:
    """Same architecture as :class:`ViTDef` with blocks stored STACKED:
    every ``params["blocks"]`` leaf has a leading ``depth`` dim.

    ``interleave=v > 1`` (with ``pp_stages=S``) selects the interleaved
    virtual-stage schedule (``pipeline_apply_interleaved``): device ``d``
    owns the ``v`` non-adjacent virtual stages ``d, d+S, ...``, so the
    stacked block rows are stored DEVICE-MAJOR (all of device 0's chunks,
    then device 1's, ...) — one ``P('pipe')`` spec still shards them; the
    sequential (non-pp) path un-permutes back to logical depth order.
    """

    image_size: int = 32
    patch_size: int = 4
    dim: int = 64
    depth: int = 4
    heads: int = 4
    mlp_ratio: int = 4
    num_classes: int = 10
    interleave: int = 1
    pp_stages: int = 0  # required when interleave > 1 (layout needs S)

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    def _vit(self) -> ViTDef:
        return ViTDef(
            image_size=self.image_size, patch_size=self.patch_size, dim=self.dim,
            depth=self.depth, heads=self.heads, mlp_ratio=self.mlp_ratio,
            num_classes=self.num_classes,
        )

    def _storage_perm(self):
        """Block-row permutation logical → storage (device-major chunks).
        Identity when interleave == 1."""
        import numpy as np  # noqa: PLC0415

        if self.interleave <= 1:
            return None
        n, v = self.pp_stages, self.interleave
        if n <= 0:
            raise ValueError("interleave > 1 requires pp_stages (stage count)")
        if self.depth % (n * v):
            raise ValueError(
                f"depth {self.depth} must divide into pp_stages*interleave="
                f"{n * v} chunks"
            )
        bpc = self.depth // (n * v)  # blocks per chunk (virtual stage)
        rows = []
        for d in range(n):
            for k in range(v):
                j = k * n + d  # logical virtual-stage index
                rows.extend(range(j * bpc, (j + 1) * bpc))
        return np.asarray(rows)

    def init(self, key, dtype=jnp.float32):
        params, state = self._vit().init(key, dtype)
        blocks = params.pop("blocks")  # list of per-block dicts → stacked
        stacked = jax.tree_util.tree_map(
            lambda *leaves: jnp.stack(leaves), *blocks
        )
        perm = self._storage_perm()
        if perm is not None:
            stacked = jax.tree_util.tree_map(lambda a: a[perm], stacked)
        params["blocks"] = stacked
        return params, state

    def pp_param_specs(self, axis: str):
        """Blocks sharded on their stacked leading dim; rest replicated."""
        from jax.sharding import PartitionSpec as P  # noqa: PLC0415

        return {
            "patch": {"w": P(), "b": P()},
            "pos": P(),
            "blocks": jax.tree_util.tree_map(
                lambda _: P(axis), self._block_leaf_template()
            ),
            "ln_f": {"scale": P(), "bias": P()},
            "head": {"w": P(), "b": P()},
        }

    def tp_param_specs(self, axis: str):
        """Pure-TP layout for the stacked-block storage (``--tp`` without
        ``--pp``): Megatron column/row sharding on the weight dims, the
        stacked leading (depth) dim unsharded.  The sequential apply path
        runs the same TP block per stacked row."""
        from jax.sharding import PartitionSpec as P  # noqa: PLC0415

        blocks = {
            "ln1": {"scale": P(), "bias": P()},
            "qkv": {"w": P(None, None, axis), "b": P(None, axis)},
            "proj": {"w": P(None, axis, None), "b": P()},
            "ln2": {"scale": P(), "bias": P()},
            "mlp1": {"w": P(None, None, axis), "b": P(None, axis)},
            "mlp2": {"w": P(None, axis, None), "b": P()},
        }
        return {
            "patch": {"w": P(), "b": P()},
            "pos": P(),
            "blocks": blocks,
            "ln_f": {"scale": P(), "bias": P()},
            "head": {"w": P(), "b": P()},
        }

    def pp_tp_param_specs(self, pp_axis: str, tp_axis: str):
        """Megatron PP×TP layout: blocks sharded over ``pp_axis`` on the
        stacked leading (depth) dim AND over ``tp_axis`` on the Megatron
        dims — qkv/mlp1 column-sharded, proj/mlp2 row-sharded, norms and
        row-output biases replicated within the stage.  Embed/head stay
        replicated (small, computed everywhere), same as plain PP."""
        from jax.sharding import PartitionSpec as P  # noqa: PLC0415

        blocks = {
            "ln1": {"scale": P(pp_axis), "bias": P(pp_axis)},
            "qkv": {"w": P(pp_axis, None, tp_axis), "b": P(pp_axis, tp_axis)},
            "proj": {"w": P(pp_axis, tp_axis, None), "b": P(pp_axis)},
            "ln2": {"scale": P(pp_axis), "bias": P(pp_axis)},
            "mlp1": {"w": P(pp_axis, None, tp_axis), "b": P(pp_axis, tp_axis)},
            "mlp2": {"w": P(pp_axis, tp_axis, None), "b": P(pp_axis)},
        }
        return {
            "patch": {"w": P(), "b": P()},
            "pos": P(),
            "blocks": blocks,
            "ln_f": {"scale": P(), "bias": P()},
            "head": {"w": P(), "b": P()},
        }

    def _block_leaf_template(self):
        return {
            "ln1": {"scale": 0, "bias": 0},
            "qkv": {"w": 0, "b": 0},
            "proj": {"w": 0, "b": 0},
            "ln2": {"scale": 0, "bias": 0},
            "mlp1": {"w": 0, "b": 0},
            "mlp2": {"w": 0, "b": 0},
        }

    def patchify(self, x):
        return patchify(x, self.patch_size)

    # -- forward -------------------------------------------------------------

    def _embed(self, params, x):
        t = _dense(params["patch"], self.patchify(x))
        check_pos_capacity(t.shape[1], params["pos"], self.image_size, self.patch_size)
        return t + params["pos"][: t.shape[1]].astype(t.dtype)[None]

    def _stage_scan(self, stage_blocks, t, attn_impl=None, tp_axis=None):
        """Run this stage's stacked blocks sequentially.  With ``tp_axis``
        each block is the Megatron-TP block (qkv/mlp1 arrive column-sharded,
        proj/mlp2 row-sharded — one psum pair per block over the tp axis)."""
        if tp_axis is not None:
            from tpu_dist.parallel.tensor import tp_ops  # noqa: PLC0415

            copy_to_tp, reduce_from_tp = tp_ops(tp_axis)
            h_dim = self.dim // self.heads

            def body(h, blk):
                return tp_block_forward(
                    blk, h, h_dim, copy_to_tp, reduce_from_tp,
                    attn_impl=attn_impl,
                ), None
        else:

            def body(h, blk):
                return block_forward(blk, h, self.heads, attn_impl=attn_impl), None

        out, _ = lax.scan(body, t, stage_blocks)
        return out

    def _finish(self, params, t):
        t = _ln_apply(params["ln_f"], t)
        return _dense(params["head"], t.mean(axis=1))

    def apply(
        self,
        params,
        state,
        x,
        *,
        train: bool = False,
        axis_name: Optional[str] = None,  # contract parity (no BN)
        pp_axis: Optional[str] = None,
        tp_axis: Optional[str] = None,
        n_microbatches: int = 0,
        attn_impl: Optional[str] = None,
    ):
        """Without ``pp_axis``: sequential scan over all blocks (reference
        semantics). With ``pp_axis``: ``params["blocks"]`` arrives holding
        only THIS stage's blocks; the batch is split into ``n_microbatches``
        (default: the stage count) and streamed through the ring.
        ``tp_axis`` (Megatron PP×TP): each stage's blocks additionally
        arrive TP-sliced (place params with :meth:`pp_tp_param_specs`);
        the stage computation runs the TP block with its psum pair.
        """
        del axis_name
        t = self._embed(params, x)
        if pp_axis is None:
            blocks = params["blocks"]
            perm = self._storage_perm()
            if perm is not None:  # storage is device-major — restore logical
                import numpy as np  # noqa: PLC0415

                inv = np.argsort(perm)
                blocks = jax.tree_util.tree_map(lambda a: a[inv], blocks)
            t = self._stage_scan(blocks, t, attn_impl, tp_axis)
            return self._finish(params, t), state

        n_stages = compat.axis_size(pp_axis)
        if self.interleave > 1 and self.pp_stages != n_stages:
            raise ValueError(
                f"model laid out for pp_stages={self.pp_stages}, mesh has "
                f"{n_stages} pipeline stages"
            )
        m = n_microbatches or n_stages
        b = t.shape[0]
        if b % m:
            raise ValueError(f"batch {b} must divide into {m} microbatches")
        micro = t.reshape(m, b // m, *t.shape[1:])
        if self.interleave > 1:
            v = self.interleave
            # local shard rows = this device's v chunks, k-major
            chunks = jax.tree_util.tree_map(
                lambda a: a.reshape(v, a.shape[0] // v, *a.shape[1:]),
                params["blocks"],
            )
            outs = pipeline_apply_interleaved(
                lambda blocks, h: self._stage_scan(blocks, h, attn_impl, tp_axis),
                chunks,
                micro,
                pp_axis,
                n_stages,
                v,
            )
        else:
            outs = pipeline_apply(
                lambda blocks, h: self._stage_scan(blocks, h, attn_impl, tp_axis),
                params["blocks"],
                micro,
                pp_axis,
                n_stages,
            )
        t = outs.reshape(b, *t.shape[1:])
        return self._finish(params, t), state


def vit_pp_tiny(num_classes: int = 10, image_size: int = 32) -> ViTPipelineDef:
    return ViTPipelineDef(image_size=image_size, num_classes=num_classes)
