"""Parameter initializers.

Matches the effective init of the reference model (``utils/model.py``), which
uses torch defaults: Kaiming-uniform with ``a=sqrt(5)`` for conv/linear
weights, uniform ``±1/sqrt(fan_in)`` for linear bias, BN scale=1 / bias=0.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp


def kaiming_uniform(key, shape, fan_in: int, a: float = math.sqrt(5.0), dtype=jnp.float32):
    """torch's default ``kaiming_uniform_(a=sqrt(5))`` for conv/linear weight."""
    gain = math.sqrt(2.0 / (1.0 + a * a))
    bound = gain * math.sqrt(3.0 / fan_in)
    return jax.random.uniform(key, shape, dtype, minval=-bound, maxval=bound)


def uniform_fan_in(key, shape, fan_in: int, dtype=jnp.float32):
    """torch's default bias init: U(±1/sqrt(fan_in))."""
    bound = 1.0 / math.sqrt(fan_in) if fan_in > 0 else 0.0
    return jax.random.uniform(key, shape, dtype, minval=-bound, maxval=bound)
