"""Attention: full (single-device) and ring (sequence-parallel) variants.

The reference has no attention code at all (SURVEY §2.3: models are conv
ResNets); its BASELINE north star adds ViT-B/16 as a data-parallel stress
test. This module goes further and makes long-context support first-class,
TPU-style:

* :func:`full_attention` — plain softmax attention; one fused XLA op chain,
  MXU-friendly einsums, f32 softmax accumulation under bf16 compute.
* :func:`ring_attention` — sequence parallelism over a mesh axis: Q stays
  local while K/V blocks rotate around the ring via ``lax.ppermute``
  (ICI-neighbor traffic only), with flash-style online-softmax accumulation
  so the full [S, S] score matrix never materializes. Per-device memory is
  O(S_local · S_block) and the sequence dimension scales with the number of
  devices on the axis. Combine with the ``data`` axis on a 2-D mesh for
  DP × SP.
* :func:`ulysses_attention` — the all-to-all alternative: tokens↔heads
  redistribution so each device runs full-sequence attention for H/n
  heads (two collectives per call; composes with the Pallas flash
  kernel). Pick by topology: ring = nearest-neighbor ICI traffic,
  ulysses = fewer collectives and flash-compatible, needs heads % n == 0.

Both operate on [B, S, H, D] (batch, sequence, heads, head_dim) and are
shape-polymorphic under ``shard_map``.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from tpu_dist.comm import compat

# Process-global default for the single-device attention implementation.
# "xla": one fused einsum/softmax chain ([S,S] scores in HBM — fine at ViT
# lengths). "flash": the Pallas tiled kernel (ops/flash_attention.py) —
# O(block²) memory, the long-context choice. The Trainer sets this from
# ``--flash_attention``; it is process-global state like the XLA compile
# cache, not per-model.
_DEFAULT_IMPL = "xla"


def set_default_attention_impl(impl: str) -> None:
    global _DEFAULT_IMPL
    if impl not in ("xla", "flash"):
        raise ValueError(f"attention impl must be 'xla' or 'flash', got {impl!r}")
    _DEFAULT_IMPL = impl


def get_default_attention_impl() -> str:
    return _DEFAULT_IMPL


def _resolve_impl(impl: Optional[str]) -> str:
    impl = impl or _DEFAULT_IMPL
    if impl not in ("xla", "flash"):
        raise ValueError(f"attention impl must be 'xla' or 'flash', got {impl!r}")
    return impl


def full_attention(q, k, v, *, causal: bool = False, impl: Optional[str] = None):
    """[B,S,H,D] x3 → [B,S,H,D]. Softmax in f32 regardless of input dtype."""
    if _resolve_impl(impl) == "flash":
        from tpu_dist.ops.flash_attention import flash_attention  # noqa: PLC0415

        return flash_attention(q, k, v, causal=causal)
    d = q.shape[-1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
    scores = scores / jnp.sqrt(jnp.float32(d))
    if causal:
        s_q, s_k = scores.shape[-2], scores.shape[-1]
        mask = jnp.tril(jnp.ones((s_q, s_k), bool), k=s_k - s_q)
        scores = jnp.where(mask, scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def ring_attention(q, k, v, axis_name: str, *, causal: bool = False):
    """Sequence-parallel attention over ``axis_name`` (ring / all-to-all CP).

    Inside ``shard_map`` with the sequence dim sharded over ``axis_name``:
    every device holds local Q/K/V blocks of shape [B, S/n, H, D]. K/V
    rotate n times around the ring (``lax.ppermute`` to the next neighbor —
    nearest-neighbor ICI traffic, overlapped by XLA with the block matmuls);
    attention is accumulated with the numerically-stable online softmax
    (running max ``m``, normalizer ``l``, accumulator ``acc``).

    ``causal`` masks by GLOBAL position: block order on the axis is the
    sequence order (device i holds positions [i·S/n, (i+1)·S/n)).
    """
    n = compat.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, s_loc, h, d = q.shape
    scale = 1.0 / jnp.sqrt(jnp.float32(d))

    qf = q.astype(jnp.float32)

    def block(scores_kv, kv_idx):
        """Scores of local Q against the K/V block originating at kv_idx."""
        kk, vv = scores_kv
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, kk.astype(jnp.float32)) * scale
        if causal:
            q_pos = my * s_loc + jnp.arange(s_loc)[:, None]        # [Sq,1]
            k_pos = kv_idx * s_loc + jnp.arange(s_loc)[None, :]    # [1,Sk]
            s = jnp.where(q_pos >= k_pos, s, -jnp.inf)
        return s, vv

    def body(carry, _):
        m, l, acc, kk, vv, kv_idx = carry
        s, vv_f = block((kk, vv), kv_idx)
        m_new = jnp.maximum(m, s.max(axis=-1))                     # [B,H,Sq]
        # guard: fully-masked rows keep m at -inf; exp(-inf - -inf) → use 0
        corr = jnp.where(jnp.isfinite(m), jnp.exp(m - m_new), 0.0)
        p = jnp.exp(s - m_new[..., None])
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        l_new = l * corr + p.sum(axis=-1)
        acc = acc * corr[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, vv_f.astype(jnp.float32)
        )
        # rotate K/V to the next ring position
        perm = [(i, (i + 1) % n) for i in range(n)]
        kk = lax.ppermute(kk, axis_name, perm)
        vv = lax.ppermute(vv, axis_name, perm)
        kv_idx = (kv_idx - 1) % n
        return (m_new, l_new, acc, kk, vv, kv_idx), None

    m0 = jnp.full((b, h, s_loc), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, s_loc), jnp.float32)
    acc0 = jnp.zeros((b, h, s_loc, d), jnp.float32)
    (m, l, acc, _, _, _), _ = lax.scan(
        body, (m0, l0, acc0, k, v, my), None, length=n
    )
    out = acc / jnp.maximum(l, 1e-30)[..., None]                   # [B,H,Sq,D]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)               # [B,Sq,H,D]


def ulysses_attention(q, k, v, axis_name: str, *, causal: bool = False,
                      impl: Optional[str] = None):
    """All-to-all sequence parallelism (the DeepSpeed-Ulysses scheme —
    the OTHER first-class long-context strategy next to the ring).

    Inside ``shard_map`` with the sequence dim sharded over ``axis_name``:
    one STACKED ``all_to_all`` (q/k/v together) redistributes tokens↔heads
    so each device holds the FULL sequence for ``H/n`` of the heads,
    ordinary single-device attention runs locally (attention never mixes
    heads), and a second ``all_to_all`` restores the token sharding. Two
    collectives per call versus the ring's ``n`` ppermutes; requires
    ``heads % n == 0``.

    Differentiable by plain autodiff (``all_to_all`` transposes to
    ``all_to_all``) — no custom VJP needed. And because the local call IS
    full-sequence attention, the Pallas flash kernel composes directly:
    ``impl="flash"`` (or the process default) runs the tiled kernel on the
    gathered sequence — flash × SP with no extra machinery.
    """
    n = compat.axis_size(axis_name)
    h = q.shape[2]
    if h % n:
        raise ValueError(
            f"ulysses sequence parallelism needs heads ({h}) divisible by "
            f"the axis size ({n}); use sp_mode='ring' otherwise"
        )

    # ONE stacked all_to_all for q/k/v (axes shifted by the leading stack
    # dim), one for the output — two collectives total, as advertised
    qkv = jnp.stack((q, k, v))  # [3, B, S/n, H, D]
    qg, kg, vg = lax.all_to_all(
        qkv, axis_name, split_axis=3, concat_axis=2, tiled=True
    )                           # each [B, S, H/n, D]
    o = full_attention(qg, kg, vg, causal=causal, impl=impl)
    return lax.all_to_all(o, axis_name, split_axis=1, concat_axis=2, tiled=True)


def attention(q, k, v, *, causal: bool = False, seq_axis: Optional[str] = None,
              impl: Optional[str] = None, sp_mode: str = "ring"):
    """Dispatch: sequence-parallel attention when a sequence axis is given
    (``sp_mode``: "ring" rotation or "ulysses" all-to-all), else full
    (``impl``/module default selecting XLA vs Pallas flash).

    Under the RING the flash impl selects
    :func:`tpu_dist.ops.flash_attention.ring_flash_attention`: the ring
    already tiles ACROSS devices (each rotation sees one [S/n, S/n] local
    tile, never a global [S, S]), and the Pallas kernels tile WITHIN the
    device, taking the per-rotation working set from O(S_local²) HBM down
    to O(block²) VMEM. Under ULYSSES the flash impl applies directly (the
    local computation is full-sequence attention)."""
    if seq_axis is not None:
        if sp_mode == "ulysses":
            return ulysses_attention(q, k, v, seq_axis, causal=causal, impl=impl)
        if sp_mode != "ring":
            raise ValueError(f"sp_mode must be 'ring' or 'ulysses', got {sp_mode!r}")
        if _resolve_impl(impl) == "flash":
            from tpu_dist.ops.flash_attention import (  # noqa: PLC0415
                ring_flash_attention,
            )

            return ring_flash_attention(q, k, v, seq_axis, causal=causal)
        return ring_attention(q, k, v, seq_axis, causal=causal)
    return full_attention(q, k, v, causal=causal, impl=impl)
