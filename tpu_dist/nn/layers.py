"""Functional layer primitives: conv / batchnorm / linear.

Design: layers are pure ``init``/``apply`` function pairs over plain pytree
dicts (no module objects). This keeps every model a jit-traceable function of
``(params, state, x)`` — the shape ``pjit``/``shard_map`` want — and makes
cross-replica SyncBatchNorm a one-argument affair (``axis_name``) instead of
a CUDA kernel (reference: ``torch.nn.SyncBatchNorm.convert_sync_batchnorm``
at ``distributed.py:59`` and apex's fused variant at ``distributed_apex.py:85``).

Layout is NHWC (channels-last): XLA:TPU tiles the trailing dimension onto the
MXU/VPU lanes, so channels-last keeps convs on the fast path without layout
transposes (the reference's NCHW is a cuDNN convention, not a TPU one).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from tpu_dist.nn import initializers as init

BN_MOMENTUM = 0.1  # torch BatchNorm2d default
BN_EPS = 1e-5


# ---------------------------------------------------------------------------
# Conv2d (bias-free, as everywhere in the reference model: utils/model.py)
# ---------------------------------------------------------------------------

def conv_init(key, in_ch: int, out_ch: int, ksize: int, dtype=jnp.float32):
    """HWIO kernel. fan_in = ksize*ksize*in_ch (torch convention)."""
    fan_in = ksize * ksize * in_ch
    w = init.kaiming_uniform(key, (ksize, ksize, in_ch, out_ch), fan_in, dtype=dtype)
    return {"w": w}


def conv_apply(params, x, stride: int = 1, padding: int = 0):
    return lax.conv_general_dilated(
        x,
        params["w"].astype(x.dtype),
        window_strides=(stride, stride),
        padding=[(padding, padding), (padding, padding)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


# ---------------------------------------------------------------------------
# BatchNorm2d with optional cross-replica sync
# ---------------------------------------------------------------------------

def bn_init(ch: int, dtype=jnp.float32):
    params = {"scale": jnp.ones((ch,), dtype), "bias": jnp.zeros((ch,), dtype)}
    state = {"mean": jnp.zeros((ch,), dtype), "var": jnp.ones((ch,), dtype)}
    return params, state


def bn_apply(
    params,
    state,
    x,
    *,
    train: bool,
    axis_name: Optional[str] = None,
    momentum: float = BN_MOMENTUM,
    eps: float = BN_EPS,
):
    """Returns ``(y, new_state)``.

    ``axis_name`` set → SyncBatchNorm: batch statistics are ``pmean``-ed over
    the mesh axis, so every replica normalizes with GLOBAL-batch statistics —
    the ~5-line TPU equivalent of the reference's native SyncBN kernels
    (SURVEY §2.2 N5). ``axis_name=None`` → per-replica statistics, matching
    plain ``BatchNorm2d`` under DDP without the SyncBN convert.

    Running stats follow torch semantics: EMA with ``momentum`` on the
    *unbiased* variance, normalization uses the *biased* batch variance.
    """
    scale = params["scale"].astype(x.dtype)
    bias = params["bias"].astype(x.dtype)

    if not train:
        mean = state["mean"].astype(x.dtype)
        var = state["var"].astype(x.dtype)
        inv = lax.rsqrt(var + eps)
        return (x - mean) * inv * scale + bias, state

    reduce_axes = tuple(range(x.ndim - 1))  # all but channel
    # Statistics in f32 even under bf16 compute: variance of bf16 sums loses
    # too many bits at CIFAR batch sizes.
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=reduce_axes)
    mean_sq = jnp.mean(jnp.square(xf), axis=reduce_axes)
    n = x.size // x.shape[-1]
    if axis_name is not None:
        mean = lax.pmean(mean, axis_name)
        mean_sq = lax.pmean(mean_sq, axis_name)
        n = n * lax.psum(1, axis_name)
    var = jnp.maximum(mean_sq - jnp.square(mean), 0.0)

    unbiased = var * (n / max(n - 1, 1)) if isinstance(n, int) else var * (n / (n - 1))
    new_state = {
        "mean": (1.0 - momentum) * state["mean"] + momentum * mean,
        "var": (1.0 - momentum) * state["var"] + momentum * unbiased,
    }
    inv = lax.rsqrt(var + eps).astype(x.dtype)
    y = (x - mean.astype(x.dtype)) * inv * scale + bias
    return y, new_state


# ---------------------------------------------------------------------------
# Linear
# ---------------------------------------------------------------------------

def linear_init(key, in_dim: int, out_dim: int, dtype=jnp.float32):
    kw, kb = jax.random.split(key)
    return {
        "w": init.kaiming_uniform(kw, (in_dim, out_dim), in_dim, dtype=dtype),
        "b": init.uniform_fan_in(kb, (out_dim,), in_dim, dtype=dtype),
    }


def linear_apply(params, x):
    return x @ params["w"].astype(x.dtype) + params["b"].astype(x.dtype)


def relu(x):
    return jnp.maximum(x, 0)


def global_avg_pool(x):
    """NHWC → NC (the reference's AdaptiveAvgPool2d((1,1)) + flatten)."""
    return jnp.mean(x, axis=(1, 2))
