"""CIFAR-style ResNet-18/34/50 (TPU-native re-design of ``utils/model.py``).

Architecture parity with the reference (``utils/model.py:61-127``):
3×3 stem without maxpool (CIFAR variant, ``:66-70``), stages
[64,128,256,512] with strides [1,2,2,2] (``:72-75``), BasicBlock
(expansion 1, ``:3-28``) for 18/34, BottleNeck (expansion 4, ``:32-59``)
for 50, global average pool + linear head (``:76-77``), 100 classes by
default (``:62``). Every conv is bias-free and followed by BatchNorm — the
property that makes SyncBN a real requirement.

Differences from the reference are layout-only: NHWC tensors, functional
``init``/``apply`` over pytree dicts (see ``tpu_dist.nn.layers``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp

from tpu_dist.nn import layers as L


@dataclass(frozen=True)
class ResNetDef:
    """Static model description; ``init``/``apply`` close over it.

    ``widths`` defaults to the reference's stage widths
    (``utils/model.py:72-75``); narrower widths give the test suite a
    fast-compiling miniature with identical code paths.
    """

    block: str  # "basic" | "bottleneck"
    stage_blocks: Tuple[int, int, int, int]
    num_classes: int = 100
    widths: Tuple[int, int, int, int] = (64, 128, 256, 512)
    # CIFAR variant (reference default): 3x3 stem, no maxpool
    # (utils/model.py:66-70). imagenet_stem=True switches to the canonical
    # 7x7/stride-2 stem + 3x3/stride-2 maxpool for 224x224 inputs.
    imagenet_stem: bool = False
    # MXU-friendly stem (TPU-only concern, MLPerf-style): compute the
    # 7x7/2 stem as a mathematically-identical 4x4/1 conv on the 2x2
    # space-to-depth transform of the input. C_in=3 leaves 125 of the
    # MXU's 128 input lanes idle for the heaviest-spatial conv of the
    # net; s2d quadruples arithmetic intensity (C_in 3→12, spatial /4)
    # without changing parameters, checkpoints, or numerics (bit-exact
    # up to f32 summation order — see tests/test_models.py). Only
    # meaningful with imagenet_stem; requires even H, W.
    s2d_stem: bool = False

    @property
    def expansion(self) -> int:
        return 1 if self.block == "basic" else 4

    # -- init ---------------------------------------------------------------

    def init(self, key, dtype=jnp.float32):
        """Returns ``(params, bn_state)`` pytrees (nested dicts/lists)."""
        keys = iter(jax.random.split(key, 1024))
        params = {}
        state = {}

        stem = self.widths[0]
        stem_k = 7 if self.imagenet_stem else 3
        params["stem_conv"] = L.conv_init(next(keys), 3, stem, stem_k, dtype)
        params["stem_bn"], state["stem_bn"] = L.bn_init(stem, dtype)

        in_ch = stem
        for si, (width, n_blocks, stride) in enumerate(
            zip(self.widths, self.stage_blocks, (1, 2, 2, 2))
        ):
            blocks_p: List[dict] = []
            blocks_s: List[dict] = []
            for bi in range(n_blocks):
                s = stride if bi == 0 else 1
                p, st, in_ch = self._block_init(next(keys), in_ch, width, s, dtype)
                blocks_p.append(p)
                blocks_s.append(st)
            params[f"stage{si + 1}"] = blocks_p
            state[f"stage{si + 1}"] = blocks_s

        params["fc"] = L.linear_init(
            next(keys), self.widths[-1] * self.expansion, self.num_classes, dtype
        )
        return params, state

    def _block_init(self, key, in_ch, width, stride, dtype):
        out_ch = width * self.expansion
        ks = iter(jax.random.split(key, 8))
        p, s = {}, {}
        if self.block == "basic":
            p["conv1"] = L.conv_init(next(ks), in_ch, width, 3, dtype)
            p["bn1"], s["bn1"] = L.bn_init(width, dtype)
            p["conv2"] = L.conv_init(next(ks), width, out_ch, 3, dtype)
            p["bn2"], s["bn2"] = L.bn_init(out_ch, dtype)
        else:
            p["conv1"] = L.conv_init(next(ks), in_ch, width, 1, dtype)
            p["bn1"], s["bn1"] = L.bn_init(width, dtype)
            p["conv2"] = L.conv_init(next(ks), width, width, 3, dtype)
            p["bn2"], s["bn2"] = L.bn_init(width, dtype)
            p["conv3"] = L.conv_init(next(ks), width, out_ch, 1, dtype)
            p["bn3"], s["bn3"] = L.bn_init(out_ch, dtype)
        if stride != 1 or in_ch != out_ch:
            p["sc_conv"] = L.conv_init(next(ks), in_ch, out_ch, 1, dtype)
            p["sc_bn"], s["sc_bn"] = L.bn_init(out_ch, dtype)
        return p, s, out_ch

    # -- apply --------------------------------------------------------------

    def apply(
        self,
        params,
        state,
        x,
        *,
        train: bool = False,
        axis_name: Optional[str] = None,
    ):
        """Forward pass. ``x``: NHWC. Returns ``(logits, new_bn_state)``.

        ``axis_name`` enables SyncBatchNorm over that mesh axis (reference
        ``distributed.py:59`` semantics); only meaningful when ``train``.
        """
        bn = dict(train=train, axis_name=axis_name)
        new_state = {}

        if self.imagenet_stem:
            if self.s2d_stem:
                y = self._stem_s2d(params["stem_conv"]["w"], x)
            else:
                y = L.conv_apply(params["stem_conv"], x, stride=2, padding=3)
        else:
            y = L.conv_apply(params["stem_conv"], x, stride=1, padding=1)
        y, new_state["stem_bn"] = L.bn_apply(params["stem_bn"], state["stem_bn"], y, **bn)
        y = L.relu(y)
        if self.imagenet_stem:
            y = jax.lax.reduce_window(
                y, -jnp.inf, jax.lax.max,
                (1, 3, 3, 1), (1, 2, 2, 1), [(0, 0), (1, 1), (1, 1), (0, 0)],
            )

        for si in range(4):
            name = f"stage{si + 1}"
            stage_state = []
            for bp, bs in zip(params[name], state[name]):
                stride = (1, 2, 2, 2)[si] if not stage_state else 1
                y, ns = self._block_apply(bp, bs, y, stride, bn)
                stage_state.append(ns)
            new_state[name] = stage_state

        y = L.global_avg_pool(y)
        logits = L.linear_apply(params["fc"], y)
        return logits, new_state

    @staticmethod
    def _stem_s2d(w, x):
        """7x7/stride-2 stem conv, computed as an equivalent 4x4/stride-1
        conv over the 2x2 space-to-depth rearrangement of the input.

        Identity: pad the kernel to 8x8 with a zero top row/left column,
        so ``y[i,j] = Σ_{a,b∈[0,8)} W8[a,b]·x[2i+a-4, 2j+b-4]``; split
        ``a = 2p+u`` (phase u over the s2d factor) and the sum factorizes
        into a 4x4 conv over ``X[m,n,(u,v,c)] = x[2m+u, 2n+v, c]`` with
        asymmetric padding (2,1). Parameters stay stored as the plain
        [7,7,3,C] kernel — checkpoints are interchangeable between the
        two stems; the rearrangement is ~9k elements at trace time.
        """
        from jax import lax as _lax  # noqa: PLC0415

        k, _, c_in, c_out = w.shape
        if k != 7:
            raise ValueError(f"s2d stem expects the 7x7 kernel, got {k}x{k}")
        w8 = jnp.pad(w, ((1, 0), (1, 0), (0, 0), (0, 0)))
        w4 = (
            w8.reshape(4, 2, 4, 2, c_in, c_out)
            .transpose(0, 2, 1, 3, 4, 5)
            .reshape(4, 4, 4 * c_in, c_out)
        )
        n, h, wd, c = x.shape
        if h % 2 or wd % 2:
            raise ValueError(f"s2d stem needs even H, W; got {h}x{wd}")
        xs = (
            x.reshape(n, h // 2, 2, wd // 2, 2, c)
            .transpose(0, 1, 3, 2, 4, 5)
            .reshape(n, h // 2, wd // 2, 4 * c)
        )
        return _lax.conv_general_dilated(
            xs,
            w4.astype(xs.dtype),
            window_strides=(1, 1),
            padding=[(2, 1), (2, 1)],
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )

    def _block_apply(self, p, s, x, stride, bn):
        ns = {}
        if self.block == "basic":
            y = L.conv_apply(p["conv1"], x, stride=stride, padding=1)
            y, ns["bn1"] = L.bn_apply(p["bn1"], s["bn1"], y, **bn)
            y = L.relu(y)
            y = L.conv_apply(p["conv2"], y, stride=1, padding=1)
            y, ns["bn2"] = L.bn_apply(p["bn2"], s["bn2"], y, **bn)
        else:
            y = L.conv_apply(p["conv1"], x, stride=1, padding=0)
            y, ns["bn1"] = L.bn_apply(p["bn1"], s["bn1"], y, **bn)
            y = L.relu(y)
            y = L.conv_apply(p["conv2"], y, stride=stride, padding=1)
            y, ns["bn2"] = L.bn_apply(p["bn2"], s["bn2"], y, **bn)
            y = L.relu(y)
            y = L.conv_apply(p["conv3"], y, stride=1, padding=0)
            y, ns["bn3"] = L.bn_apply(p["bn3"], s["bn3"], y, **bn)

        if "sc_conv" in p:
            sc = L.conv_apply(p["sc_conv"], x, stride=stride, padding=0)
            sc, ns["sc_bn"] = L.bn_apply(p["sc_bn"], s["sc_bn"], sc, **bn)
        else:
            sc = x
        return L.relu(y + sc), ns


def resnet18(num_classes: int = 100) -> ResNetDef:
    """Reference factory parity: ``utils/model.py:115-117``."""
    return ResNetDef("basic", (2, 2, 2, 2), num_classes)


def resnet34(num_classes: int = 100) -> ResNetDef:
    """Reference factory parity: ``utils/model.py:120-122``."""
    return ResNetDef("basic", (3, 4, 6, 3), num_classes)


def resnet50(num_classes: int = 100) -> ResNetDef:
    """Reference factory parity: ``utils/model.py:125-127``."""
    return ResNetDef("bottleneck", (3, 4, 6, 3), num_classes)


def resnet50_imagenet(num_classes: int = 1000, s2d_stem: bool = False) -> ResNetDef:
    """Canonical ImageNet ResNet-50 (7x7 stem + maxpool; ~25.6M params) —
    for the BASELINE ResNet-50/ImageNet-1k config. ``s2d_stem=True``
    computes the identical stem via space-to-depth (TPU MXU utilization;
    same params/checkpoints)."""
    return ResNetDef(
        "bottleneck", (3, 4, 6, 3), num_classes,
        imagenet_stem=True, s2d_stem=s2d_stem,
    )
