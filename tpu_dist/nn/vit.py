"""Vision Transformer (ViT) family — the BASELINE north-star transformer
config ("ViT-B/16 / ImageNet-1k ... stress allreduce on transformer grads",
BASELINE.json configs[4]; the reference itself has no transformer, SURVEY
§2.3).

Same functional contract as :class:`~tpu_dist.nn.resnet.ResNetDef`:
``init(key) -> (params, state)`` / ``apply(params, state, x, train=,
axis_name=, seq_axis=)``. ``state`` is empty (no BatchNorm — LayerNorm
needs no cross-replica sync), so ViT slots into the same Trainer/steps.

``seq_axis`` switches the attention to the sequence-parallel ring variant
(:func:`tpu_dist.nn.attention.ring_attention`) for long-context training
over a 2-D DP×SP mesh; patch tokens must then arrive sharded over that axis.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

from tpu_dist.comm import compat

from tpu_dist.nn import attention as attn_lib


def _ln_init(dim):
    return {"scale": jnp.ones((dim,)), "bias": jnp.zeros((dim,))}


def _ln_apply(p, x, eps=1e-6):
    xf = x.astype(jnp.float32)
    mu = xf.mean(-1, keepdims=True)
    var = ((xf - mu) ** 2).mean(-1, keepdims=True)
    y = (xf - mu) * jax.lax.rsqrt(var + eps)
    return (y * p["scale"] + p["bias"]).astype(x.dtype)


def _dense_init(key, din, dout):
    kw, kb = jax.random.split(key)
    # transformer practice: truncated-normal-ish small init for stability
    w = jax.random.normal(kw, (din, dout)) * (din ** -0.5)
    return {"w": w, "b": jnp.zeros((dout,))}


def _dense(p, x):
    return x @ p["w"].astype(x.dtype) + p["b"].astype(x.dtype)


def _dense_local(p, x):
    """Matmul only — bias is added by the caller (after any TP psum)."""
    return x @ p["w"].astype(x.dtype)


def patchify(x, patch_size: int):
    """[B, H, W, 3] → [B, N, patch_dim] in row-major patch order (shared by
    ViTDef and ViTMoEDef)."""
    b, h, w, c = x.shape
    ph = pw = patch_size
    x = x.reshape(b, h // ph, ph, w // pw, pw, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, (h // ph) * (w // pw), ph * pw * c)


def block_forward(blk, t, heads: int, attn_impl: Optional[str] = None):
    """One standard (full-attention) transformer block on [B, S, D].
    Shared by ViTDef's sequential path and the pipeline-parallel wrapper.
    ``attn_impl`` pins the attention implementation at build time (None =
    process default at trace time)."""
    b, s, dim = t.shape
    h_dim = dim // heads
    y = _ln_apply(blk["ln1"], t)
    qkv = _dense(blk["qkv"], y).reshape(b, s, heads, 3, h_dim)
    q, k, v = (qkv[:, :, :, i, :] for i in range(3))
    o = attn_lib.full_attention(q, k, v, impl=attn_impl)
    t = t + _dense(blk["proj"], o.reshape(b, s, dim))
    y = _ln_apply(blk["ln2"], t)
    y = jax.nn.gelu(_dense(blk["mlp1"], y))
    return t + _dense(blk["mlp2"], y)


def tp_block_forward(
    blk,
    t,
    h_dim: int,
    copy_to_tp,
    reduce_from_tp,
    *,
    seq_axis: Optional[str] = None,
    sp_mode: str = "ring",
    attn_impl: Optional[str] = None,
):
    """One Megatron-TP transformer block on [B, S, D]: qkv/mlp1 arrive
    column-sharded (local heads / local hidden), proj/mlp2 row-sharded;
    ``copy_to_tp``/``reduce_from_tp`` are the conjugate identity/psum pair
    from :func:`tpu_dist.parallel.tensor.tp_ops`.  Shared by ViTDef's
    sequential TP path and the pipeline-parallel stage scan (PP×TP —
    Megatron's layout: TP inside each pipeline stage)."""
    y = copy_to_tp(_ln_apply(blk["ln1"], t))
    qkv = _dense(blk["qkv"], y)  # col-sharded under TP: local heads
    b, s, qkv_dim = qkv.shape
    h_loc = qkv_dim // (3 * h_dim)
    # layout [heads, 3, h_dim]: a contiguous column shard is whole heads
    qkv = qkv.reshape(b, s, h_loc, 3, h_dim)
    q, k, v = (qkv[:, :, :, i, :] for i in range(3))
    o = attn_lib.attention(
        q, k, v, seq_axis=seq_axis, sp_mode=sp_mode, impl=attn_impl
    )
    proj = reduce_from_tp(_dense_local(blk["proj"], o.reshape(b, s, h_loc * h_dim)))
    t = t + proj + blk["proj"]["b"].astype(t.dtype)
    y = copy_to_tp(_ln_apply(blk["ln2"], t))
    y = jax.nn.gelu(_dense(blk["mlp1"], y))  # col-sharded hidden
    return t + reduce_from_tp(_dense_local(blk["mlp2"], y)) + blk["mlp2"]["b"].astype(t.dtype)


def check_pos_capacity(n_tokens: int, pos_table, image_size: int, patch_size: int):
    """Loud error when the input has more patch tokens than the positional
    table (smaller inputs are fine — they use the leading positions)."""
    if n_tokens > pos_table.shape[0]:
        raise ValueError(
            f"input has {n_tokens} patch tokens but the positional embedding "
            f"holds {pos_table.shape[0]} (image_size={image_size}, "
            f"patch_size={patch_size}); build the model with the matching "
            f"image_size"
        )


@dataclass(frozen=True)
class ViTDef:
    image_size: int = 224
    patch_size: int = 16
    dim: int = 768
    depth: int = 12
    heads: int = 12
    mlp_ratio: int = 4
    num_classes: int = 1000
    pool: str = "mean"  # mean-pool tokens (cls-free keeps seq sharding even)

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    def init(self, key, dtype=jnp.float32):
        keys = iter(jax.random.split(key, 16 + 8 * self.depth))
        p: dict = {}
        patch_dim = self.patch_size * self.patch_size * 3
        p["patch"] = _dense_init(next(keys), patch_dim, self.dim)
        p["pos"] = jax.random.normal(next(keys), (self.n_patches, self.dim)) * 0.02
        blocks = []
        for _ in range(self.depth):
            blocks.append(
                {
                    "ln1": _ln_init(self.dim),
                    "qkv": _dense_init(next(keys), self.dim, 3 * self.dim),
                    "proj": _dense_init(next(keys), self.dim, self.dim),
                    "ln2": _ln_init(self.dim),
                    "mlp1": _dense_init(next(keys), self.dim, self.mlp_ratio * self.dim),
                    "mlp2": _dense_init(next(keys), self.mlp_ratio * self.dim, self.dim),
                }
            )
        p["blocks"] = blocks
        p["ln_f"] = _ln_init(self.dim)
        p["head"] = _dense_init(next(keys), self.dim, self.num_classes)
        if dtype != jnp.float32:
            p = jax.tree_util.tree_map(lambda t: t.astype(dtype), p)
        return p, {}

    # -- apply ---------------------------------------------------------------

    def tp_param_specs(self, axis: str):
        """PartitionSpec pytree for Megatron TP over ``axis``: qkv/mlp1
        column-sharded, proj/mlp2 row-sharded, everything else replicated.
        Use for ``shard_map`` in/out specs AND for placing the params
        (``NamedSharding(mesh, spec)`` per leaf)."""
        from jax.sharding import PartitionSpec as P  # noqa: PLC0415

        rep = {"w": P(), "b": P()}
        block = {
            "ln1": {"scale": P(), "bias": P()},
            "qkv": {"w": P(None, axis), "b": P(axis)},
            "proj": {"w": P(axis, None), "b": P()},
            "ln2": {"scale": P(), "bias": P()},
            "mlp1": {"w": P(None, axis), "b": P(axis)},
            "mlp2": {"w": P(axis, None), "b": P()},
        }
        return {
            "patch": dict(rep),
            "pos": P(),
            "blocks": [dict(block) for _ in range(self.depth)],
            "ln_f": {"scale": P(), "bias": P()},
            "head": dict(rep),
        }

    def patchify(self, x):
        """[B, H, W, 3] → [B, N, patch_dim] in row-major patch order."""
        return patchify(x, self.patch_size)

    def apply(
        self,
        params,
        state,
        x,
        *,
        train: bool = False,
        axis_name: Optional[str] = None,  # unused (no BN); kept for contract
        seq_axis: Optional[str] = None,
        sp_mode: str = "ring",
        tp_axis: Optional[str] = None,
        tokens: Optional[jnp.ndarray] = None,
        pos_offset: int = 0,
        attn_impl: Optional[str] = None,
    ):
        """Forward. Either ``x`` as images [B,H,W,3] (patchified here) or
        pre-sharded ``tokens`` [B, S_local, patch_dim] for sequence-parallel
        runs (with ``pos_offset`` the global index of the first local token).

        ``tp_axis``: Megatron tensor parallelism — qkv/mlp1 arrive
        column-sharded (local heads / local hidden), proj/mlp2 row-sharded
        with one ``psum`` each; params must be placed with
        :meth:`tp_param_specs`. Composable with neither ``seq_axis`` nor
        SyncBN (there is no BN).
        """
        del axis_name
        if tokens is None:
            tokens = self.patchify(x)
            if seq_axis is not None:
                # x arrived replicated over the seq axis: each device keeps
                # only its contiguous token chunk (ring attention owns the
                # cross-chunk interaction)
                n_sp = compat.axis_size(seq_axis)
                if tokens.shape[1] % n_sp:
                    raise ValueError(
                        f"sequence of {tokens.shape[1]} patch tokens does not "
                        f"divide over {n_sp} sequence-parallel devices — "
                        f"tokens would be silently dropped"
                    )
                s_loc = tokens.shape[1] // n_sp
                tokens = jax.lax.dynamic_slice_in_dim(
                    tokens, jax.lax.axis_index(seq_axis) * s_loc, s_loc, axis=1
                )
        t = _dense(params["patch"], tokens)
        pos = params["pos"].astype(t.dtype)
        if seq_axis is not None:
            idx = jax.lax.axis_index(seq_axis)
            s_loc = t.shape[1]
            pos = jax.lax.dynamic_slice_in_dim(pos, idx * s_loc + pos_offset, s_loc)
        else:
            check_pos_capacity(t.shape[1], pos, self.image_size, self.patch_size)
            pos = pos[: t.shape[1]]  # smaller inputs use the leading positions
        t = t + pos[None]

        if tp_axis is not None:
            from tpu_dist.parallel.tensor import tp_ops  # noqa: PLC0415

            copy_to_tp, reduce_from_tp = tp_ops(tp_axis)
        else:
            copy_to_tp = reduce_from_tp = lambda v: v

        h_dim = self.dim // self.heads
        for blk in params["blocks"]:
            t = tp_block_forward(
                blk, t, h_dim, copy_to_tp, reduce_from_tp,
                seq_axis=seq_axis, sp_mode=sp_mode, attn_impl=attn_impl,
            )

        t = _ln_apply(params["ln_f"], t)
        pooled = t.mean(axis=1)
        if seq_axis is not None:
            # token mean over the full (sharded) sequence
            pooled = jax.lax.pmean(pooled, seq_axis)
        return _dense(params["head"], pooled), state


def vit_b16(num_classes: int = 1000, image_size: int = 224) -> ViTDef:
    """ViT-B/16 (86M params at 1000 classes) — BASELINE configs[4]."""
    return ViTDef(image_size=image_size, patch_size=16, dim=768, depth=12,
                  heads=12, num_classes=num_classes)


def vit_s16(num_classes: int = 1000, image_size: int = 224) -> ViTDef:
    return ViTDef(image_size=image_size, patch_size=16, dim=384, depth=12,
                  heads=6, num_classes=num_classes)


def vit_tiny(num_classes: int = 10, image_size: int = 32) -> ViTDef:
    """CIFAR-sized: patch 4 over 32x32 → 64 tokens; for tests/smokes."""
    return ViTDef(image_size=image_size, patch_size=4, dim=64, depth=2,
                  heads=4, num_classes=num_classes)
