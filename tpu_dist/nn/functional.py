"""Loss and classification functionals (the ``nn.CrossEntropyLoss`` /
``accuracy`` surface of the reference, ``distributed.py:62`` and
``utils/util.py:50-64``), written to fuse cleanly under jit."""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def cross_entropy(logits, labels, *, reduction: str = "mean", label_smoothing: float = 0.0):
    """Softmax cross-entropy with integer labels (optionally smoothed).

    Computed in f32 regardless of the compute dtype: the log-sum-exp is the
    numerically fragile spot under bf16. ``label_smoothing=s`` mixes the
    one-hot target with the uniform distribution (torch semantics).
    """
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
    if label_smoothing > 0.0:
        s = label_smoothing
        uniform = -logp.mean(axis=-1)
        nll = (1.0 - s) * nll + s * uniform
    if reduction == "mean":
        return nll.mean()
    if reduction == "sum":
        return nll.sum()
    return nll


def topk_correct(logits, labels, ks: Sequence[int] = (1, 5)):
    """Per-batch counts of top-k hits — the core of the reference's
    ``accuracy(output, target, topk)`` (``utils/util.py:50-64``), returned as
    counts (not percentages) so shards can be summed exactly across replicas.
    """
    maxk = min(max(ks), logits.shape[-1])  # clamp: num_classes may be < 5
    _, pred = lax.top_k(logits, maxk)  # [B, maxk]
    hits = pred == labels[:, None]
    return tuple(jnp.sum(hits[:, : min(k, maxk)]) for k in ks)


def accuracy(logits, labels, topk: Sequence[int] = (1,)) -> Tuple:
    """Percentages, reference signature (``utils/util.py:50``)."""
    counts = topk_correct(logits, labels, topk)
    b = logits.shape[0]
    return tuple(c.astype(jnp.float32) * (100.0 / b) for c in counts)
