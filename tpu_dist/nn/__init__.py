from tpu_dist.nn import functional as functional  # noqa: F401
from tpu_dist.nn import layers as layers  # noqa: F401
from tpu_dist.nn.resnet import (  # noqa: F401
    ResNetDef,
    resnet18,
    resnet34,
    resnet50,
)
