"""ViT with Mixture-of-Experts FFN blocks — expert parallelism end to end.

No reference counterpart (SURVEY §2.3: no EP anywhere). Every transformer
block's dense MLP is replaced by a Switch-style top-1 MoE
(:class:`tpu_dist.parallel.expert.MoE`); under an ``expert`` mesh axis the
expert weights live sharded (``ep_param_specs``) and tokens are exchanged
with one ``all_to_all`` per block, per direction.

Functional contract matches :class:`ViTDef` (``init``/``apply`` with
``ep_axis`` instead of ``tp_axis``), so it slots into the same train step
through ``param_specs`` + a model kwarg.

Gradient note: no conjugate ops are needed inside the model — the block
input carries DATA (each device holds different tokens), not a replica, and
``apply_ep``'s ``all_to_all`` transposes into the exact reverse
``all_to_all``. The whole correction lives in the train step's per-leaf
reduction (``tpu_dist/train/step.py::_ep_grad_reduce``): expert-sharded
leaves ``pmean(data)/n_ep``, replicated leaves ``pmean(data, expert)``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

from tpu_dist.nn import attention as attn_lib
from tpu_dist.nn.vit import (
    _dense,
    _dense_init,
    _ln_apply,
    _ln_init,
    check_pos_capacity,
    patchify,
)
from tpu_dist.parallel.expert import MoE


@dataclass(frozen=True)
class ViTMoEDef:
    image_size: int = 32
    patch_size: int = 4
    dim: int = 64
    depth: int = 2
    heads: int = 4
    n_experts: int = 8
    capacity_factor: float = 2.0
    top_k: int = 1  # experts per token (1 = Switch, 2 = GShard-style)
    num_classes: int = 10

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def moe(self) -> MoE:
        return MoE(self.n_experts, self.capacity_factor, self.top_k)

    def init(self, key, dtype=jnp.float32):
        keys = iter(jax.random.split(key, 8 + 4 * self.depth))
        p: dict = {}
        patch_dim = self.patch_size * self.patch_size * 3
        p["patch"] = _dense_init(next(keys), patch_dim, self.dim)
        p["pos"] = jax.random.normal(next(keys), (self.n_patches, self.dim)) * 0.02
        blocks = []
        for _ in range(self.depth):
            blocks.append(
                {
                    "ln1": _ln_init(self.dim),
                    "qkv": _dense_init(next(keys), self.dim, 3 * self.dim),
                    "proj": _dense_init(next(keys), self.dim, self.dim),
                    "ln2": _ln_init(self.dim),
                    "moe": self.moe.init(next(keys), self.dim, 4 * self.dim),
                }
            )
        p["blocks"] = blocks
        p["ln_f"] = _ln_init(self.dim)
        p["head"] = _dense_init(next(keys), self.dim, self.num_classes)
        if dtype != jnp.float32:
            p = jax.tree_util.tree_map(lambda t: t.astype(dtype), p)
        return p, {}

    def ep_param_specs(self, axis: str):
        """Experts sharded on their leading dim; everything else replicated."""
        from jax.sharding import PartitionSpec as P  # noqa: PLC0415

        block = {
            "ln1": {"scale": P(), "bias": P()},
            "qkv": {"w": P(), "b": P()},
            "proj": {"w": P(), "b": P()},
            "ln2": {"scale": P(), "bias": P()},
            "moe": {"router": P(), "w_in": P(axis), "w_out": P(axis)},
        }
        return {
            "patch": {"w": P(), "b": P()},
            "pos": P(),
            "blocks": [dict(block) for _ in range(self.depth)],
            "ln_f": {"scale": P(), "bias": P()},
            "head": {"w": P(), "b": P()},
        }

    def patchify(self, x):
        return patchify(x, self.patch_size)

    def apply(
        self,
        params,
        state,
        x,
        *,
        train: bool = False,
        axis_name: Optional[str] = None,  # unused (no BN); contract parity
        ep_axis: Optional[str] = None,
        attn_impl: Optional[str] = None,
    ):
        """``ep_axis`` set: the batch arrives sharded over BOTH the data and
        expert axes (the expert axis doubles as a data axis everywhere
        outside the MoE), expert weights arrive sharded
        (:meth:`ep_param_specs`), and each block's MoE exchanges tokens with
        its expert owners via ``all_to_all``.

        Training returns the depth-averaged router load-balancing loss in
        the state dict (``{"moe_aux_loss": scalar}``) — the train step adds
        ``moe_aux_coef`` times it to the objective and drops the key before
        the state is stored."""
        del axis_name
        tokens = self.patchify(x)
        t = _dense(params["patch"], tokens)
        check_pos_capacity(t.shape[1], params["pos"], self.image_size, self.patch_size)
        t = t + params["pos"][: t.shape[1]].astype(t.dtype)[None]

        h_dim = self.dim // self.heads
        b = t.shape[0]
        aux_total = jnp.zeros((), jnp.float32)
        for blk in params["blocks"]:
            y = _ln_apply(blk["ln1"], t)
            qkv = _dense(blk["qkv"], y)
            s = qkv.shape[1]
            qkv = qkv.reshape(b, s, self.heads, 3, h_dim)
            q, k, v = (qkv[:, :, :, i, :] for i in range(3))
            o = attn_lib.full_attention(q, k, v, impl=attn_impl)
            t = t + _dense(blk["proj"], o.reshape(b, s, self.dim))

            y = _ln_apply(blk["ln2"], t)
            flat = y.reshape(b * s, self.dim)
            if ep_axis is None:
                out, aux = self.moe.apply_dense(blk["moe"], flat, with_aux=True)
            else:
                out, aux = self.moe.apply_ep(
                    blk["moe"]["router"],
                    blk["moe"]["w_in"],
                    blk["moe"]["w_out"],
                    flat,
                    ep_axis,
                    with_aux=True,
                )
            aux_total = aux_total + aux.astype(jnp.float32)
            t = t + out.reshape(b, s, self.dim)

        t = _ln_apply(params["ln_f"], t)
        logits = _dense(params["head"], t.mean(axis=1))
        if train:
            return logits, {"moe_aux_loss": aux_total / self.depth}
        return logits, state


def vit_moe_tiny(num_classes: int = 10, image_size: int = 32) -> ViTMoEDef:
    return ViTMoEDef(image_size=image_size, num_classes=num_classes)
