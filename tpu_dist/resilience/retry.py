"""Exponential-backoff retry for transient host I/O (``--ckpt_io_retries``).

Scope is deliberately narrow: *host-side, idempotent* operations — the
checkpoint writers, whose write-to-temp + atomic-rename discipline makes a
failed attempt leave nothing behind. Collectives are explicitly out of
scope (a retried collective on one process deadlocks the others).

Determinism: the delay sequence is ``base_delay * 2**attempt`` capped at
``max_delay`` — a pure function of the attempt index, no jitter, no wall
clock reads — and the sleep itself is injectable, so tests assert the
exact schedule without sleeping.
"""

from __future__ import annotations

import time
from typing import Callable, Optional, Tuple, Type


def backoff_delays(
    retries: int, base_delay: float = 0.05, max_delay: float = 2.0
) -> Tuple[float, ...]:
    """The deterministic sleep schedule: one entry per retry."""
    return tuple(
        min(base_delay * (2.0**i), max_delay) for i in range(max(0, retries))
    )


def retry_call(
    fn: Callable,
    *args,
    retries: int = 0,
    base_delay: float = 0.05,
    max_delay: float = 2.0,
    retry_on: Tuple[Type[BaseException], ...] = (OSError,),
    sleep: Optional[Callable[[float], None]] = None,
    describe: str = "",
    **kwargs,
):
    """Call ``fn(*args, **kwargs)``; on a ``retry_on`` exception, sleep the
    next backoff delay and try again, up to ``retries`` extra attempts.
    The final failure re-raises the last exception unmodified (so callers'
    error handling — e.g. the emergency-save donation-hazard match — sees
    the real error, not a wrapper)."""
    if retries <= 0:
        return fn(*args, **kwargs)
    do_sleep = sleep if sleep is not None else time.sleep
    delays = backoff_delays(retries, base_delay, max_delay)
    for attempt, delay in enumerate(delays):
        try:
            return fn(*args, **kwargs)
        except retry_on as e:
            from tpu_dist.metrics.logging import rank0_print  # noqa: PLC0415
            from tpu_dist.obs import counters  # noqa: PLC0415

            counters.inc("io.retries")
            rank0_print(
                f"WARNING: transient {'I/O' if not describe else describe} "
                f"failure (attempt {attempt + 1}/{retries + 1}): {e} — "
                f"retrying in {delay:g}s"
            )
            do_sleep(delay)
    return fn(*args, **kwargs)  # last attempt: errors propagate
