"""Deterministic fault injection — the chaos harness behind ``--fault_plan``.

A *fault plan* is a semicolon-separated list of clauses::

    site@key=value[:key=value...]

Every trigger is a **deterministic coordinate** — a call count, an epoch, a
step, a batch index — never wall-clock time, so a plan replays identically
run after run (the property the bit-identical chaos tests in
``tests/test_resilience.py`` rely on). Sites:

``ckpt_write@call=K[:times=N][:errno=5]``
    Raise ``OSError(errno)`` from the K-th ``_write_npz``/shard write call
    (1-based, counted process-wide), for N consecutive calls (default 1).
    With ``--ckpt_io_retries`` the write succeeds once the clause is
    exhausted — the transient-EIO story.
``ckpt_corrupt@epoch=E[:mode=truncate|bitflip][:seed=S][:frac=0.5]``
    After ``ckpt_E.npz`` (or its sharded manifest) publishes, truncate it
    to ``frac`` of its bytes or flip 8 seeded bits in place — the torn /
    silently-corrupted newest checkpoint the restore ladder must survive.
``nan_loss@step=S[:epoch=E]``
    Report a NaN training loss at step S (of epoch E; any epoch when
    omitted) — drives the existing NaN-guard/auto-recover path.
``sigterm@step=S[:epoch=E]``
    Deliver a **real** ``SIGTERM`` to this process at step S — exercises
    the preemption-graceful shutdown end to end, signal delivery included.
``rank_kill@step=S:rank=R[:epoch=E]``
    Deliver a **real** ``SIGKILL`` to process rank R at step S — the
    hard-death half of the elastic drill (docs/resilience.md "Elastic
    training"): no handler runs, no emergency save, the rank is simply
    gone, and the elastic launcher must relaunch the survivors at a
    reduced world size. The trainer passes its process rank into
    :func:`on_step`; a clause pinning a rank never fires on a process
    whose rank is unknown.
``loader_stall@batch=B[:epoch=E]``
    Kill the data-loader producer thread before it publishes batch B
    (it exits without its end-of-epoch sentinel, exactly like a thread
    torn down at interpreter shutdown) — the consumer watchdog must turn
    this into a clear error instead of hanging the epoch.
``hang@step=S[:epoch=E][:rank=R][:seconds=T]``
    WEDGE this process at step S: the hook spins in a sleep loop (no
    exception, no exit code, heartbeat frozen — the live-but-silent
    failure no cooperative shutdown can see). The launcher watchdog's
    whole forensic chain exists for this site: frozen-beat detection,
    SIGUSR1 stack dump (which names this very loop), SIGTERM→SIGKILL
    escalation, postmortem bundle (docs/observability.md "Crash
    forensics"). ``seconds`` bounds the hang (0 = forever, the default);
    SIGTERM does NOT unwedge it — the cooperative flag is checked at
    step boundaries this process will never reach again.

Each clause fires ``times`` times (default 1) and then disarms. Injection
points call the ``on_*`` hooks below; with no plan installed every hook is
a single attribute read + ``None`` check — and all hooks are host-side, so
the traced train step is unchanged whether or not a plan is armed (audited
by TD105 in ``tpu_dist.analysis``). Every firing increments the
``faults.injected`` telemetry counter (``tpu_dist.obs.counters``), so a
chaos run's history records how many faults actually landed.

This module must not import jax. (``tpu_dist.obs.counters`` is
jax-free by the same contract.)
"""

from __future__ import annotations

import dataclasses
import os
import re
import signal
from typing import Dict, FrozenSet, List, Optional

from tpu_dist.obs import counters as _counters

ENV_VAR = "TPU_DIST_FAULT_PLAN"

# action names surfaced to the trainer by on_step()
NAN_LOSS = "nan_loss"
SIGTERM = "sigterm"
RANK_KILL = "rank_kill"
HANG = "hang"

SITES = (
    "ckpt_write", "ckpt_corrupt", "nan_loss", "sigterm", "loader_stall",
    "rank_kill", "hang",
)

#: Sites that act at the step/batch grain — refused with --fused_epoch
#: (the whole epoch is one jit call; they would silently never fire).
STEPWISE_SITES = frozenset(
    ("nan_loss", "sigterm", "loader_stall", "rank_kill", "hang")
)

_CKPT_NAME_RE = re.compile(r"ckpt_(\d+)\.(?:npz|manifest\.json)$")

_INT_KEYS = {"call", "times", "errno", "epoch", "step", "batch", "seed", "rank"}
_ALLOWED_KEYS = {
    "ckpt_write": {"call", "times", "errno"},
    "ckpt_corrupt": {"epoch", "mode", "seed", "frac", "times"},
    "nan_loss": {"step", "epoch", "times"},
    "sigterm": {"step", "epoch", "times"},
    "loader_stall": {"batch", "epoch", "times"},
    "rank_kill": {"step", "rank", "epoch", "times"},
    "hang": {"step", "epoch", "rank", "seconds", "times"},
}
_REQUIRED_KEYS = {
    "ckpt_write": {"call"},
    "ckpt_corrupt": {"epoch"},
    "nan_loss": {"step"},
    "sigterm": {"step"},
    "loader_stall": {"batch"},
    "rank_kill": {"step", "rank"},
    "hang": {"step"},
}


class FaultPlanError(ValueError):
    """Malformed ``--fault_plan`` spec."""


@dataclasses.dataclass
class FaultClause:
    site: str
    params: Dict[str, object]
    fired: int = 0

    @property
    def times(self) -> int:
        return int(self.params.get("times", 1))

    def armed(self) -> bool:
        return self.fired < self.times

    def matches(self, **coords) -> bool:
        """Armed AND every coordinate the clause pins equals the site's
        current coordinate (params absent from ``coords`` are ignored —
        e.g. ``epoch`` left unpinned matches every epoch)."""
        if not self.armed():
            return False
        for key, want in self.params.items():
            if key in ("times", "mode", "seed", "frac", "errno", "seconds"):
                continue
            if key in coords and coords[key] != want:
                return False
        return True


class FaultPlan:
    """Parsed fault plan + the per-site deterministic counters."""

    def __init__(self, clauses: List[FaultClause], spec: str = ""):
        self.clauses = clauses
        self.spec = spec
        self.ckpt_write_calls = 0  # process-wide _write_npz call counter

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        clauses: List[FaultClause] = []
        for raw in spec.split(";"):
            raw = raw.strip()
            if not raw:
                continue
            if "@" not in raw:
                raise FaultPlanError(
                    f"fault clause {raw!r} has no trigger — expected "
                    "site@key=value[:key=value...]"
                )
            site, _, rest = raw.partition("@")
            site = site.strip()
            if site not in SITES:
                raise FaultPlanError(
                    f"unknown fault site {site!r}; have {SITES}"
                )
            params: Dict[str, object] = {}
            for kv in rest.split(":"):
                if "=" not in kv:
                    raise FaultPlanError(
                        f"fault clause {raw!r}: bad parameter {kv!r} "
                        "(expected key=value)"
                    )
                key, _, val = kv.partition("=")
                key = key.strip()
                if key not in _ALLOWED_KEYS[site]:
                    raise FaultPlanError(
                        f"fault site {site!r} does not take {key!r}; "
                        f"allowed: {sorted(_ALLOWED_KEYS[site])}"
                    )
                if key in _INT_KEYS:
                    try:
                        params[key] = int(val)
                    except ValueError as e:
                        raise FaultPlanError(
                            f"fault clause {raw!r}: {key} must be an "
                            f"integer, got {val!r}"
                        ) from e
                elif key in ("frac", "seconds"):
                    params[key] = float(val)
                else:
                    params[key] = val.strip()
            missing = _REQUIRED_KEYS[site] - set(params)
            if missing:
                raise FaultPlanError(
                    f"fault clause {raw!r} is missing required "
                    f"parameter(s) {sorted(missing)}"
                )
            mode = params.get("mode", "truncate")
            if site == "ckpt_corrupt" and mode not in ("truncate", "bitflip"):
                raise FaultPlanError(
                    f"ckpt_corrupt mode must be truncate|bitflip, got {mode!r}"
                )
            clauses.append(FaultClause(site, params))
        if not clauses:
            raise FaultPlanError(f"fault plan {spec!r} contains no clauses")
        return cls(clauses, spec)

    def _matching(self, site: str, **coords) -> List[FaultClause]:
        return [
            c for c in self.clauses if c.site == site and c.matches(**coords)
        ]


# --------------------------------------------------------------------------
# Module-level plan registry (one plan per process, like the jax config
# globals this package already uses for the compile cache).
# --------------------------------------------------------------------------

_PLAN: Optional[FaultPlan] = None


def _record_fired(site: str) -> None:
    """Telemetry: every fault that actually lands is counted (total and
    per-site), so a chaos run's history shows the injection schedule."""
    _counters.inc("faults.injected")
    _counters.inc(f"faults.{site}")


def install(plan) -> FaultPlan:
    """Install a :class:`FaultPlan` (or parse a spec string) as THE active
    plan; returns it. Counters start fresh."""
    global _PLAN
    _PLAN = plan if isinstance(plan, FaultPlan) else FaultPlan.parse(plan)
    return _PLAN


def clear() -> None:
    global _PLAN
    _PLAN = None


def active() -> Optional[FaultPlan]:
    return _PLAN


def configure(spec: Optional[str]) -> Optional[FaultPlan]:
    """Config-layer entry point (the Trainer calls this once per
    construction): install ``spec``, falling back to ``$TPU_DIST_FAULT_PLAN``
    when None; with neither set, any previously-installed plan is CLEARED —
    a resumed run without ``--fault_plan`` must not replay the crashed
    run's faults."""
    spec = spec or os.environ.get(ENV_VAR)
    if spec:
        return install(spec)
    clear()
    return None


# --------------------------------------------------------------------------
# Injection hooks. Zero-cost when off: one global read + None check.
# --------------------------------------------------------------------------


def on_ckpt_write() -> None:
    """Called at the top of every checkpoint file write attempt (plain npz,
    shard file, manifest). Raises the injected ``OSError`` when an armed
    ``ckpt_write`` clause covers this call count. Retried attempts count as
    new calls, so ``call=1:times=2`` fails the first two ATTEMPTS — a
    2-retry ladder then succeeds on the third."""
    plan = _PLAN
    if plan is None:
        return
    plan.ckpt_write_calls += 1
    for c in plan.clauses:
        if c.site != "ckpt_write" or not c.armed():
            continue
        first = int(c.params["call"])
        if first <= plan.ckpt_write_calls < first + c.times:
            c.fired += 1
            _record_fired("ckpt_write")
            eno = int(c.params.get("errno", 5))  # EIO
            raise OSError(
                eno,
                f"[fault-injected] checkpoint write failure "
                f"(call {plan.ckpt_write_calls}, clause {c.params})",
            )


def on_ckpt_published(path: str) -> Optional[str]:
    """Called after a checkpoint file is atomically published. Corrupts the
    file in place when an armed ``ckpt_corrupt`` clause matches its epoch;
    returns the corruption mode applied (for logging) or None."""
    plan = _PLAN
    if plan is None:
        return None
    m = _CKPT_NAME_RE.search(os.path.basename(path))
    if not m:
        return None
    epoch = int(m.group(1))
    for c in plan._matching("ckpt_corrupt", epoch=epoch):
        c.fired += 1
        _record_fired("ckpt_corrupt")
        mode = str(c.params.get("mode", "truncate"))
        if mode == "truncate":
            truncate_file(path, frac=float(c.params.get("frac", 0.5)))
        else:
            bitflip_file(path, seed=int(c.params.get("seed", 0)))
        return mode
    return None


def on_step(epoch: int, step: int, rank: Optional[int] = None) -> FrozenSet[str]:
    """Called once per completed train step (host side). Returns the set of
    actions the trainer must apply ({'nan_loss'}); a matching ``sigterm``
    clause delivers a REAL signal to this process right here, and a
    matching ``rank_kill`` clause (step + the caller's ``rank``) delivers
    a REAL ``SIGKILL`` — the hard rank death the elastic launcher must
    survive. ``rank=None`` (callers that don't know their rank) never
    matches a rank-pinned clause."""
    plan = _PLAN
    if plan is None:
        return frozenset()
    actions = set()
    for c in plan._matching("nan_loss", epoch=epoch, step=step):
        c.fired += 1
        _record_fired("nan_loss")
        actions.add(NAN_LOSS)
    for c in plan._matching("sigterm", epoch=epoch, step=step):
        c.fired += 1
        _record_fired("sigterm")
        actions.add(SIGTERM)
        os.kill(os.getpid(), signal.SIGTERM)
    for c in plan._matching("rank_kill", epoch=epoch, step=step, rank=rank):
        c.fired += 1
        _record_fired("rank_kill")
        actions.add(RANK_KILL)
        # hard death by design: no handler, no emergency save, no exit
        # code discipline — the process is simply gone mid-run
        os.kill(os.getpid(), signal.SIGKILL)
    for c in plan._matching("hang", epoch=epoch, step=step, rank=rank):
        c.fired += 1
        _record_fired("hang")
        actions.add(HANG)
        # live-but-silent wedge by design: no exception, no signal, the
        # heartbeat counter simply stops advancing — only an EXTERNAL
        # watchdog (SIGUSR1 dump names this loop, then SIGKILL) ends it
        _hang(float(c.params.get("seconds", 0)))
    return frozenset(actions)


def _hang(seconds: float = 0) -> None:
    """Spin in a sleep loop — deterministic stand-in for a deadlocked
    collective / stuck I/O. ``seconds <= 0`` hangs forever (the drill
    case: the watchdog's SIGKILL is the only way out); a bound makes the
    site usable in in-process tests. SIGUSR1 interrupts a sleep, the
    faulthandler dump runs, and the loop resumes — exactly a real wedge."""
    import time  # noqa: PLC0415 — keep the module import-light (jax-free)

    deadline = time.monotonic() + seconds if seconds > 0 else None
    while deadline is None or time.monotonic() < deadline:
        time.sleep(0.25)


def on_loader_batch(batch: int, epoch: Optional[int] = None) -> Optional[str]:
    """Called by the loader's producer thread before publishing ``batch``.
    Returns ``'die'`` when an armed ``loader_stall`` clause matches — the
    producer then exits without its sentinel, simulating a thread killed
    mid-epoch."""
    plan = _PLAN
    if plan is None:
        return None
    coords = {"batch": batch}
    if epoch is not None:
        coords["epoch"] = epoch
    for c in plan._matching("loader_stall", **coords):
        c.fired += 1
        _record_fired("loader_stall")
        return "die"
    return None


# --------------------------------------------------------------------------
# Corruption primitives (also used directly by tests).
# --------------------------------------------------------------------------


def truncate_file(path: str, frac: float = 0.5) -> None:
    """Truncate ``path`` to ``frac`` of its size — a torn write."""
    size = os.path.getsize(path)
    keep = max(1, int(size * frac)) if size else 0
    # tpu-dist: ignore[TD002] — fault-injection harness: runs only on the
    # process that owns the file it is deliberately corrupting
    with open(path, "r+b") as f:
        f.truncate(keep)


def bitflip_file(path: str, seed: int = 0, nbits: int = 8) -> None:
    """Flip ``nbits`` seeded-pseudo-random bits in the body of ``path`` —
    silent corruption the zip directory may not notice. Deterministic: a
    simple LCG over (seed, i), no RNG state, no wall clock."""
    size = os.path.getsize(path)
    if size == 0:
        return
    # skip the first 64 bytes so the zip magic stays intact and the file
    # still LOOKS like a checkpoint (the integrity layer must catch it)
    lo = min(64, size - 1)
    span = max(1, size - lo)
    # tpu-dist: ignore[TD002] — fault-injection harness (see truncate_file)
    with open(path, "r+b") as f:
        x = (seed * 6364136223846793005 + 1442695040888963407) & (2**64 - 1)
        for _ in range(nbits):
            x = (x * 6364136223846793005 + 1442695040888963407) & (2**64 - 1)
            off = lo + (x >> 33) % span
            f.seek(off)
            b = f.read(1)
            f.seek(off)
            f.write(bytes([b[0] ^ (1 << (x % 8))]))
