"""Resilience subsystem: deterministic fault injection, preemption-graceful
shutdown, and transient-I/O retry (docs/resilience.md).

Pod-scale TPU practice treats preemption and restart as the steady state
(MLPerf on TPU-v3 pods, arXiv:1909.09756; concurrency-limits on TPUs,
arXiv:2011.03641), so failure handling here is a *tested subsystem*, not
prose:

* :mod:`tpu_dist.resilience.faults` — a seeded, config/env-driven fault
  plan (``--fault_plan`` / ``TPU_DIST_FAULT_PLAN``) that can raise
  ``OSError`` from the k-th checkpoint write, truncate or bit-flip a
  published checkpoint, poison the loss with NaN, kill the data-loader
  producer, and deliver a real ``SIGTERM`` — all through host-side
  injection points that are no-ops when no plan is installed (the TD105
  jaxpr audit asserts the traced step is byte-identical either way).
* :mod:`tpu_dist.resilience.preemption` — cooperative SIGTERM handling:
  the handler sets a flag, the trainer finishes the in-flight step, runs
  the emergency-save discipline, and the process exits with
  :data:`PREEMPTION_EXIT_CODE` (propagated by ``cli/launch.py``).
* :mod:`tpu_dist.resilience.retry` — exponential-backoff retry with
  deterministic delays and an injectable sleep, wrapped around the
  checkpoint writers (``--ckpt_io_retries``).

This package must stay import-light (no jax): the fault hooks sit on hot
host paths and the analysis CLI imports rule metadata without a backend.
"""

from tpu_dist.resilience.faults import (  # noqa: F401
    FaultPlan,
    FaultPlanError,
    active,
    clear,
    configure,
    install,
)
from tpu_dist.resilience.preemption import (  # noqa: F401
    PREEMPTION_EXIT_CODE,
    PreemptedError,
)
from tpu_dist.resilience.retry import retry_call  # noqa: F401
