"""Preemption-graceful shutdown (SIGTERM → finish step → snapshot → exit).

TPU preemption delivers ``SIGTERM``, not ``KeyboardInterrupt`` — Python's
default disposition kills the process mid-step and every un-checkpointed
step is lost. The contract here:

1. :func:`install` swaps in a handler that only sets a flag (the one thing
   that is async-signal-safe to do; collectives and file I/O are not).
2. The trainer polls :func:`requested` at the step grain, FINISHES the
   in-flight step, and raises :class:`PreemptedError`.
3. ``Trainer.fit`` catches it exactly like ``KeyboardInterrupt``: the
   ``_emergency_save`` discipline runs (mid-epoch exact snapshot, the
   poisoned-state and cross-process-sharded refusals included), then the
   error propagates.
4. ``cli/train.py`` maps it to :data:`PREEMPTION_EXIT_CODE` and
   ``cli/launch.py`` propagates that code (and forwards its own SIGTERM to
   children first) — so an orchestrator can distinguish "preempted, resume
   me" from a real failure.

``PreemptedError`` subclasses ``BaseException`` (like
``KeyboardInterrupt``) so stray ``except Exception`` blocks cannot swallow
a shutdown request.
"""

from __future__ import annotations

import signal
import threading

#: Process exit code for a preemption-graceful shutdown. 75 = BSD
#: EX_TEMPFAIL ("temporary failure; user is invited to retry") — exactly
#: the resume-me semantics, and distinct from both clean exit (0) and the
#: uncaught-SIGTERM death (128+15) a non-cooperative process shows.
PREEMPTION_EXIT_CODE = 75


class PreemptedError(BaseException):
    """Cooperative shutdown in progress (SIGTERM observed at a step/epoch
    boundary). The emergency snapshot has NOT yet run when this is raised —
    ``Trainer.fit`` runs it on the way out."""


_REQUESTED = False
_NOT_INSTALLED = object()


def _handler(signum, frame):  # noqa: ARG001 — signal-handler signature
    global _REQUESTED
    # flag write only — CPython runs Python-level handlers between
    # bytecodes, so this is safe at any interruption point. The goodput
    # ledger charges the shutdown tail from the cooperative boundary
    # (Trainer.fit's except site), not from here: time spent REACHING
    # the boundary stays in the bucket that actually used it.
    _REQUESTED = True


def install():
    """Install the cooperative SIGTERM handler. Returns an opaque token for
    :func:`restore`. No-op (token still valid) off the main thread, where
    CPython forbids ``signal.signal`` — a Trainer driven from a worker
    thread simply keeps the process's existing disposition."""
    if threading.current_thread() is not threading.main_thread():
        return _NOT_INSTALLED
    try:
        prev = signal.signal(signal.SIGTERM, _handler)
    except ValueError:  # non-main interpreter contexts
        return _NOT_INSTALLED
    return prev


def restore(token) -> None:
    """Undo :func:`install` (pass its return value)."""
    if token is _NOT_INSTALLED:
        return
    signal.signal(
        signal.SIGTERM, token if token is not None else signal.SIG_DFL
    )


def requested() -> bool:
    """True once SIGTERM has been observed (sticky until :func:`clear`)."""
    return _REQUESTED


def clear() -> None:
    global _REQUESTED
    _REQUESTED = False
